"""Tests for common: node state machine, messages, IPC, storage."""

import multiprocessing as mp
import os
import queue
import time

import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import NodeExitReason, NodeStatus
from dlrover_tpu.common.multi_process import (
    SharedDict,
    SharedLock,
    SharedMemory,
    SharedQueue,
    attach_shared_memory,
    create_shared_memory,
)
from dlrover_tpu.common.node import Node, NodeResource, is_allowed_transition
from dlrover_tpu.common.storage import (
    KeepLatestStepStrategy,
    KeepStepIntervalStrategy,
    PosixDiskStorage,
)


class TestNode:
    def test_status_flow(self):
        node = Node(node_id=0)
        assert node.update_status(NodeStatus.PENDING)
        assert node.update_status(NodeStatus.RUNNING)
        assert node.start_time is not None
        # illegal: RUNNING -> PENDING
        assert not node.update_status(NodeStatus.PENDING)
        assert node.update_status(NodeStatus.FAILED)
        assert node.finish_time is not None
        assert not is_allowed_transition(NodeStatus.DELETED, NodeStatus.RUNNING)

    def test_relaunch(self):
        node = Node(node_id=3, max_relaunch_count=2)
        node.exit_reason = NodeExitReason.KILLED
        assert not node.is_unrecoverable_failure()
        node.relaunch_count = 2
        assert node.is_unrecoverable_failure()
        node.relaunch_count = 0
        node.exit_reason = NodeExitReason.FATAL_ERROR
        assert node.is_unrecoverable_failure()

        node.exit_reason = NodeExitReason.KILLED
        new = node.get_relaunch_node_info(new_id=7)
        assert new.id == 7
        assert new.rank_index == node.rank_index
        assert new.relaunch_count == 1
        assert new.status == NodeStatus.INITIAL

    def test_resource(self):
        r = NodeResource(cpu=4, memory_mb=8192, tpu_chips=4, tpu_type="v5p")
        r2 = NodeResource.from_dict(r.to_dict())
        assert r2 == r


class TestComm:
    def test_roundtrip(self):
        msg = comm.CommWorld(
            rdzv_name="elastic-training",
            round=2,
            world={0: 4, 1: 4},
            coordinator_addr="10.0.0.1:8899",
        )
        data = comm.serialize_message(msg)
        out = comm.deserialize_message(data)
        assert out == msg

    def test_restricted_unpickle(self):
        import pickle

        class Evil:
            def __reduce__(self):
                return (os.system, ("true",))

        payload = pickle.dumps(Evil())
        with pytest.raises(Exception):
            comm.deserialize_message(payload)

    def test_find_free_port(self):
        p = comm.find_free_port()
        assert 0 < p < 65536


class TestIPC:
    def test_shared_queue(self):
        q = SharedQueue("test-q", create=True)
        client = SharedQueue("test-q", create=False)
        client.put({"step": 5})
        assert q.qsize() == 1
        assert client.get(timeout=5) == {"step": 5}
        with pytest.raises(queue.Empty):
            client.get(timeout=0.2)
        q.close()

    def test_shared_dict(self):
        d = SharedDict("test-d", create=True)
        client = SharedDict("test-d", create=False)
        client.set("rank0", {"step": 1})
        d.set("rank1", {"step": 2})
        assert client.as_dict() == {"rank0": {"step": 1}, "rank1": {"step": 2}}
        assert client.pop("rank0") == {"step": 1}
        assert client.get("rank0", "gone") == "gone"
        d.close()

    def test_shared_lock(self):
        lock = SharedLock("test-l", create=True)
        client = SharedLock("test-l", create=False)
        assert client.acquire(blocking=False)
        assert lock.locked()
        # a different thread (different owner id) cannot release it
        import threading

        results = []
        t = threading.Thread(target=lambda: results.append(lock.release()))
        t.start()
        t.join()
        assert results == [False]
        assert client.release()
        assert not lock.locked()
        assert not client.release()  # releasing an unlocked lock is a no-op
        lock.close()

    def test_force_release_breaks_dead_owner_lock(self):
        lock = SharedLock("test-fr", create=True)
        client = SharedLock("test-fr", create=False)
        assert client.acquire(blocking=False)
        # lock-handoff: the server side releases on behalf of the client
        assert lock.force_release()
        assert not lock.locked()
        assert not lock.force_release()  # idempotent on unlocked
        lock.close()

    def test_server_exists_probes_liveness(self):
        from dlrover_tpu.common.multi_process import (
            _socket_path,
            server_exists,
        )

        q = SharedQueue("test-alive", create=True)
        assert server_exists("test-alive")
        q.close()
        # dead socket file left behind must probe False (and get cleaned)
        with open(_socket_path("test-stale"), "w"):
            pass
        assert not server_exists("test-stale")
        assert not os.path.exists(_socket_path("test-stale"))
        assert not server_exists("test-never-existed")

    def test_shared_memory_survives_process(self):
        name = f"dlrover-tpu-test-{os.getpid()}"
        p = mp.get_context("spawn").Process(target=_shm_child, args=(name,))
        p.start()
        p.join()
        assert p.exitcode == 0
        shm = attach_shared_memory(name)
        assert shm is not None
        assert bytes(shm.buf[:5]) == b"hello"
        shm.close()
        shm.unlink()
        assert attach_shared_memory(name) is None


def _shm_child(n):
    shm = create_shared_memory(n, 1024)
    shm.buf[:5] = b"hello"
    shm.close()  # close mapping but do NOT unlink


class TestStorage:
    def test_atomic_write_read(self, tmp_path):
        storage = PosixDiskStorage()
        path = str(tmp_path / "ckpt" / "model.bin")
        storage.write(b"\x00\x01payload", path)
        assert storage.read(path) == b"\x00\x01payload"
        storage.write_state_dict({"w": [1, 2, 3]}, path)
        assert storage.read_state_dict(path) == {"w": [1, 2, 3]}
        assert storage.read(str(tmp_path / "missing")) is None

    def test_keep_latest_strategy(self, tmp_path):
        strat = KeepLatestStepStrategy(max_to_keep=2, checkpoint_dir=str(tmp_path))
        storage = PosixDiskStorage(strat)
        for step in (10, 20, 30):
            d = tmp_path / str(step)
            d.mkdir()
            storage.commit(step, success=True)
        assert not (tmp_path / "10").exists()
        assert (tmp_path / "20").exists()
        assert (tmp_path / "30").exists()

    def test_keep_interval_strategy(self, tmp_path):
        strat = KeepStepIntervalStrategy(keep_interval=100, checkpoint_dir=str(tmp_path))
        storage = PosixDiskStorage(strat)
        for step in (100, 150):
            (tmp_path / str(step)).mkdir()
            storage.commit(step, success=True)
        assert (tmp_path / "100").exists()
        assert not (tmp_path / "150").exists()
