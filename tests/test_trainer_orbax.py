"""ElasticTrainer facade + orbax-interoperable checkpoints."""

import os
import time

import jax
import numpy as np
import optax
import pytest

from dlrover_tpu.accel.strategy import Strategy
from dlrover_tpu.ckpt.orbax_compat import (
    OrbaxCheckpointer,
    export_to_orbax,
    load_from_orbax,
)
from dlrover_tpu.ckpt.saver import AsyncCheckpointSaver
from dlrover_tpu.models import init_sharded_state, tiny
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.trainer.elastic.trainer import (
    ElasticTrainer,
    TrainerConfig,
)


class _Tokens:
    def __init__(self, n=64, seq=32, vocab=256, seed=0):
        rng = np.random.default_rng(seed)
        self.data = rng.integers(0, vocab, (n, seq + 1), dtype=np.int32)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return {"x": self.data[i][:-1], "y": self.data[i][1:]}


class TestOrbaxCompat:
    def test_export_is_readable_by_plain_orbax(self, tmp_path):
        """The export must open with stock orbax APIs — true interop,
        not just our own reader."""
        import optax as _optax
        import orbax.checkpoint as ocp

        mesh = build_mesh(MeshConfig(fsdp=4, dp=2))
        cfg = tiny()
        tx = _optax.adamw(1e-3)
        state, _ = init_sharded_state(jax.random.PRNGKey(0), cfg, mesh, tx)
        path = str(tmp_path / "orbax_ckpt")
        export_to_orbax(state.params, path)

        with ocp.StandardCheckpointer() as ckptr:
            raw = ckptr.restore(path)
        got = raw["embed"]["tokens"]
        np.testing.assert_allclose(
            np.asarray(got),
            np.asarray(state.params["embed"]["tokens"]),
        )

    def test_load_restores_shardings(self, tmp_path):
        mesh = build_mesh(MeshConfig(fsdp=8))
        cfg = tiny()
        tx = optax.adamw(1e-3)
        state, _ = init_sharded_state(jax.random.PRNGKey(0), cfg, mesh, tx)
        path = str(tmp_path / "orbax_ckpt2")
        export_to_orbax(state.params, path)
        restored = load_from_orbax(path, state.params)
        leaf = restored["embed"]["tokens"]
        assert leaf.sharding == state.params["embed"]["tokens"].sharding

    def test_orbax_checkpointer_facade(self, tmp_path):
        ckptr = OrbaxCheckpointer(str(tmp_path / "mgr"))
        state = {"w": jax.numpy.arange(8.0), "n": jax.numpy.int32(3)}
        from dlrover_tpu.ckpt.checkpointer import StorageType

        assert ckptr.save_checkpoint(5, state, StorageType.DISK)
        step, restored = ckptr.load_checkpoint(state)
        assert step == 5
        np.testing.assert_allclose(
            np.asarray(restored["w"]), np.arange(8.0)
        )
        ckptr.close()


class TestElasticTrainer:
    @pytest.fixture(autouse=True)
    def _saver(self):
        AsyncCheckpointSaver.reset()
        AsyncCheckpointSaver.start_async_saving_ckpt(local_shard_num=1)
        yield
        AsyncCheckpointSaver.reset()

    def _trainer(self, ckpt_dir, **overrides):
        return ElasticTrainer(
            model_cfg=tiny(),
            tx=optax.adamw(1e-2),
            dataset=_Tokens(),
            trainer_cfg=TrainerConfig(
                batch_size=8,
                seq_len=32,
                ckpt_dir=ckpt_dir,
                save_memory_interval=2,
                save_storage_interval=4,
                report_metrics=False,
                log_interval=1,
                **overrides,
            ),
            strategy=Strategy(mesh=MeshConfig(dp=8), dtype="float32"),
        )

    def test_lr_scale_applied_with_injected_hyperparams(self, tmp_path):
        """Master-published batch_size_factor rescales the LR when the
        optimizer carries injected hyperparams (linear-scaling rule)."""
        import json
        import optax

        cfg_file = tmp_path / "paral.json"
        json.dump(
            {
                "dataloader": {"batch_size": 8, "version": 1},
                "optimizer": {"batch_size_factor": 2.0},
            },
            open(cfg_file, "w"),
        )
        t = ElasticTrainer(
            model_cfg=tiny(),
            tx=optax.inject_hyperparams(optax.adamw)(learning_rate=1e-2),
            dataset=_Tokens(),
            trainer_cfg=TrainerConfig(
                batch_size=8, seq_len=32, report_metrics=False,
                log_interval=1,
            ),
            strategy=Strategy(mesh=MeshConfig(dp=8), dtype="float32"),
        )
        t.dataloader._config_file = str(cfg_file)
        t.train(num_steps=1)
        assert float(
            t.state.opt_state.hyperparams["learning_rate"]
        ) == pytest.approx(2e-2)

    def test_trains_and_resumes(self, tmp_path):
        ckpt_dir = str(tmp_path / "flash")
        t1 = self._trainer(ckpt_dir)
        losses = []
        t1._metrics_hook = lambda s, m: losses.append(float(m["loss"]))
        t1.train(num_steps=6)
        assert t1.global_step == 6
        assert losses[-1] < losses[0]  # it actually learns
        # final in-memory save. save() honors the skip-never-block
        # contract: on a loaded box the agent saver can still hold the
        # shard lock persisting an earlier step, and every interval
        # save this run may have been skipped for the same reason —
        # retry (bounded) so the resume below has a recent step, which
        # is what this test is about (not save-lock timing)
        deadline = time.time() + 30
        while not t1.save() and time.time() < deadline:
            time.sleep(0.2)
        t1.close()

        # a "restarted worker": fresh trainer, same ckpt dir
        t2 = self._trainer(ckpt_dir)
        assert t2.global_step >= 4  # resumed, not from scratch
        t2.train(num_steps=t2.global_step + 2)
        t2.close()


class TestTrainerSurface:
    """Eval loop + LR schedules + metric logging (ref
    atorch_trainer.py:127's evaluate/lr_scheduler/log surface)."""

    def test_build_optimizer_schedules(self):
        """The schedule drives hyperparams['learning_rate'] per step:
        warmup rises, cosine decays to ~0 at total_steps."""
        import jax.numpy as jnp
        from dlrover_tpu.trainer.elastic.trainer import build_optimizer

        tx = build_optimizer(
            "adamw", lr=1e-2, schedule="cosine", warmup_steps=5,
            total_steps=50,
        )
        params = {"w": jnp.ones(4)}
        st = tx.init(params)
        lrs = []
        for _ in range(50):
            _, st = tx.update({"w": jnp.ones(4)}, st, params)
            lrs.append(float(st.hyperparams["learning_rate"]))
        assert lrs[0] < lrs[4]              # warmup rising
        assert max(lrs) == pytest.approx(1e-2, rel=0.05)
        assert lrs[-1] < 0.1 * max(lrs)     # cosine decayed

    def test_retune_scale_composes_with_schedule(self, tmp_path):
        """The master's batch-size factor must survive the schedule's
        per-step learning_rate rewrite: it lives in retune_scale."""
        import json
        from dlrover_tpu.trainer.elastic.trainer import build_optimizer

        cfg_file = tmp_path / "paral.json"
        json.dump(
            {
                "dataloader": {"batch_size": 8, "version": 1},
                "optimizer": {"batch_size_factor": 2.0},
            },
            open(cfg_file, "w"),
        )
        t = ElasticTrainer(
            model_cfg=tiny(),
            tx=build_optimizer(
                "adamw", lr=1e-2, schedule="cosine", warmup_steps=2,
                total_steps=100,
            ),
            dataset=_Tokens(),
            trainer_cfg=TrainerConfig(
                batch_size=8, seq_len=32, report_metrics=False,
                log_interval=1,
            ),
            strategy=Strategy(mesh=MeshConfig(dp=8), dtype="float32"),
        )
        t.dataloader._config_file = str(cfg_file)
        t.train(num_steps=3)
        hp = t.state.opt_state.hyperparams
        assert float(hp["retune_scale"]) == pytest.approx(2.0)
        # learning_rate still follows the schedule (warmup region)
        assert 0 < float(hp["learning_rate"]) <= 1e-2
        assert t.current_lr() is not None

    def test_eval_loop_runs_and_reports(self, tmp_path):
        """evaluate() runs grad-free over the eval set; the periodic
        eval inside train() surfaces eval_loss through the hook with no
        user-side loop code."""
        seen = []
        t = ElasticTrainer(
            model_cfg=tiny(),
            tx=optax.adamw(1e-2),
            dataset=_Tokens(),
            eval_dataset=_Tokens(n=64, seed=5),
            trainer_cfg=TrainerConfig(
                batch_size=8, seq_len=32, report_metrics=False,
                log_interval=1, eval_interval=2, eval_steps=3,
            ),
            strategy=Strategy(mesh=MeshConfig(dp=8), dtype="float32"),
            metrics_hook=lambda s, m: seen.append(m),
        )
        before = t.evaluate()["eval_loss"]
        t.train(num_steps=4)
        after = t.evaluate()["eval_loss"]
        assert np.isfinite(before) and np.isfinite(after)
        assert any("eval_loss" in m for m in seen), seen
        # params trained on the same token distribution: eval improves
        assert after < before

    @pytest.mark.skipif(
        jax.__version_info__ < (0, 5, 0),
        reason="interleaved pp schedule needs PartitionId SPMD support",
    )
    def test_eval_runs_under_interleaved_pipeline(self):
        """ADVICE r3 (medium): evaluate() crashed for pp_schedule=
        'interleaved' — the eval step scanned the [pp, v, lc] chunked
        layout as [pp, L/pp]. The eval step now threads the strategy's
        resolved virtual stages into pipeline_forward."""
        t = ElasticTrainer(
            model_cfg=tiny(num_layers=4),
            tx=optax.adamw(1e-2),
            dataset=_Tokens(),
            eval_dataset=_Tokens(n=32, seed=5),
            trainer_cfg=TrainerConfig(
                batch_size=8, seq_len=32, report_metrics=False,
                log_interval=1, eval_steps=2,
            ),
            strategy=Strategy(
                mesh=MeshConfig(pp=2, dp=4), dtype="float32",
                num_microbatches=4, pp_schedule="interleaved",
                pp_virtual=2,
            ),
        )
        t.train(num_steps=2)
        m = t.evaluate()
        assert np.isfinite(m["eval_loss"]), m

    @pytest.mark.skipif(
        jax.__version_info__ < (0, 5, 0),
        reason="interleaved pp schedule needs PartitionId SPMD support",
    )
    def test_eval_interleaved_via_opts_route(self):
        """The schedule may arrive as an OPT name instead of
        pp_schedule (candidates / auto_accelerate return pre-apply
        strategies) — eval must resolve the chunked layout from either
        source (Strategy.resolved_virtual)."""
        t = ElasticTrainer(
            model_cfg=tiny(num_layers=4),
            tx=optax.adamw(1e-2),
            dataset=_Tokens(),
            eval_dataset=_Tokens(n=32, seed=5),
            trainer_cfg=TrainerConfig(
                batch_size=8, seq_len=32, report_metrics=False,
                log_interval=1, eval_steps=2,
            ),
            strategy=Strategy(
                mesh=MeshConfig(pp=2, dp=4), dtype="float32",
                num_microbatches=4, opts=("interleaved",),
            ),
        )
        t.train(num_steps=2)
        m = t.evaluate()
        assert np.isfinite(m["eval_loss"]), m

    def test_train_metrics_reach_master_collector(self):
        """The full metric leg: trainer publishes scalars ->
        TrainingMonitor forwards -> master collector stores them."""
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.agent.monitor import (
            TrainingMonitor, report_runtime_metrics,
        )
        from dlrover_tpu.master.local_master import LocalJobMaster

        m = LocalJobMaster(port=0, node_num=1)
        m.prepare()
        c = MasterClient(m.addr, node_id=0)
        try:
            report_runtime_metrics(7, loss=1.25, lr=3e-4, eval_loss=2.0)
            mon = TrainingMonitor(c, interval=999)
            mon._tick()
            got = m.metric_collector.train_metrics[0]
            assert got["step"] == 7
            assert got["loss"] == pytest.approx(1.25)
            assert got["eval_loss"] == pytest.approx(2.0)
            assert got["lr"] == pytest.approx(3e-4)
        finally:
            c.close()
            m.stop()


def test_trainer_grad_accum(tmp_path):
    """TrainerConfig.grad_accum threads through the strategy into the
    train step; training still converges."""
    t = ElasticTrainer(
        model_cfg=tiny(),
        tx=optax.adamw(1e-2),
        dataset=_Tokens(),
        trainer_cfg=TrainerConfig(
            batch_size=16, seq_len=32, report_metrics=False,
            log_interval=1, grad_accum=2,
        ),
        strategy=Strategy(mesh=MeshConfig(dp=8), dtype="float32"),
    )
    assert t.accel.strategy.grad_accum == 2
    losses = []
    t._metrics_hook = lambda s, m: losses.append(float(m["loss"]))
    t.train(num_steps=5)
    assert losses[-1] < losses[0]


@pytest.mark.slow  # ~13s: multi-eval trainer run; budget-gated out of tier-1
def test_save_best_and_early_stopping(tmp_path):
    """save_best persists a DISK checkpoint on eval improvement; early
    stopping halts after `patience` evals without improvement (an
    eval set DISJOINT from training stops improving quickly at this
    scale)."""
    AsyncCheckpointSaver.reset()
    AsyncCheckpointSaver.start_async_saving_ckpt(local_shard_num=1)
    try:
        ckpt_dir = str(tmp_path / "best")
        t = ElasticTrainer(
            model_cfg=tiny(),
            tx=optax.adamw(5e-2),  # aggressive: overfits train fast
            dataset=_Tokens(),
            eval_dataset=_Tokens(n=32, seed=99),  # disjoint tokens
            trainer_cfg=TrainerConfig(
                batch_size=8, seq_len=32, report_metrics=False,
                log_interval=50, eval_interval=2, eval_steps=2,
                ckpt_dir=ckpt_dir, save_memory_interval=10**6,
                save_storage_interval=10**6,
                save_best=True, save_best_min_interval_s=0.0,
                early_stopping_patience=2,
            ),
            strategy=Strategy(mesh=MeshConfig(dp=8), dtype="float32"),
        )
        t.train(num_steps=60)
        stopped_at = t.global_step
        assert stopped_at < 60, "early stopping never fired"
        # the best checkpoint lives in its OWN directory (periodic saves
        # must never supersede it) with the sidecar recording its loss
        import json, os
        best_dir = os.path.join(ckpt_dir, "best")
        best_step = t._best_ckptr.engine.latest_step(best_dir)
        assert best_step >= 0
        side = json.load(open(os.path.join(best_dir, "best_eval.json")))
        assert side["step"] == best_step
        recorded_best = side["eval_loss"]
        t.close()

        # a restarted run must NOT regress the stored best: its first
        # (worse) eval is not declared a new best
        t2 = ElasticTrainer(
            model_cfg=tiny(),
            tx=optax.adamw(5e-2),
            dataset=_Tokens(),
            eval_dataset=_Tokens(n=32, seed=99),
            trainer_cfg=TrainerConfig(
                batch_size=8, seq_len=32, report_metrics=False,
                log_interval=50, eval_interval=2, eval_steps=2,
                ckpt_dir=ckpt_dir, save_memory_interval=10**6,
                save_storage_interval=10**6,
                save_best=True, save_best_min_interval_s=0.0,
            ),
            strategy=Strategy(mesh=MeshConfig(dp=8), dtype="float32"),
        )
        assert t2._best_eval_loss == pytest.approx(recorded_best)
        t2.close()
    finally:
        AsyncCheckpointSaver.reset()


def test_build_optimizer_repo_optimizers():
    """The repo's own AGD and 8-bit AdamW ride the same schedule +
    retune_scale surface as the optax bases."""
    import jax.numpy as jnp
    from dlrover_tpu.trainer.elastic.trainer import build_optimizer

    for name in ("agd", "adamw_8bit", "sgd"):
        tx = build_optimizer(
            name, lr=1e-2, schedule="cosine", total_steps=10,
            weight_decay=0.01,
        )
        params = {"w": jnp.ones(8192)}
        st = tx.init(params)
        u, st = tx.update({"w": jnp.ones(8192) * 1e-3}, st, params)
        assert "retune_scale" in st.hyperparams
        assert float(jnp.abs(u["w"]).sum()) > 0
