"""Preemption-aware survival (ISSUE 11): eviction grace-window drain,
master-side scheduled departures, and the Brain's preemption pricing.

The worker leg (drain state machine, emergency checkpoint, `eviction`
goodput booking) runs on a real tiny trainer; the master leg (notice
handling, rendezvous exclusion, pre-armed resize, budget-free
relaunch) and the Brain leg (eviction-aware floors, drain-latency-
priced dwell) are pure control-plane tests. The full end-to-end kill /
evict / outage scenarios live in tools/chaos.py and
tests/test_chaos_harness.py.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from dlrover_tpu.common import comm, faults
from dlrover_tpu.common.constants import NodeExitReason, NodeStatus
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.job_auto_scaler import JobAutoScaler
from dlrover_tpu.master.job_manager import JobManager, NodeEvent
from dlrover_tpu.master.paral_config import ParalConfigService
from dlrover_tpu.master.rdzv_manager import RendezvousManager
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.obs.aggregate import TelemetryAggregator
from dlrover_tpu.obs.flight_recorder import FlightRecorder
from dlrover_tpu.obs.goodput import CATEGORIES, GoodputLedger
from dlrover_tpu.obs.metrics import MetricsRegistry
from dlrover_tpu.obs.trace import SpanTracer


# ---------------------------------------------------------------------------
# goodput: the `eviction` category
# ---------------------------------------------------------------------------
class TestGoodputEviction:
    def test_category_registered_with_top_priority(self):
        assert CATEGORIES[0] == "eviction"

    def test_episode_books_seconds(self):
        led = GoodputLedger(tracer=SpanTracer(enabled=True))
        led.eviction_begin()
        time.sleep(0.03)
        led.eviction_end()
        rep = led.snapshot()
        assert rep.seconds["eviction"] >= 0.025

    def test_eviction_outranks_ckpt_spans(self):
        """Checkpoint work INSIDE the drain window books as eviction
        (the preemption's price), never double-counted as ckpt_block."""
        tr = SpanTracer(enabled=True)
        led = GoodputLedger(tracer=tr)
        led.eviction_begin()
        with tr.span("ckpt_save"):
            time.sleep(0.03)
        led.eviction_end()
        rep = led.snapshot()
        assert rep.seconds["eviction"] >= 0.025
        assert rep.seconds["ckpt_block"] == pytest.approx(0.0, abs=1e-3)
        assert rep.closure_error_pct < 1.0

    def test_mark_interval_accepts_eviction(self):
        led = GoodputLedger(tracer=SpanTracer(enabled=True))
        time.sleep(0.03)  # the marked interval must lie in the past
        t = time.monotonic_ns() - 25_000_000
        led.mark_interval("eviction", t, t + 20_000_000)
        assert led.snapshot().seconds["eviction"] == pytest.approx(
            0.020, abs=5e-3
        )


# ---------------------------------------------------------------------------
# fault layer: @N scripting + kill kind + new sites
# ---------------------------------------------------------------------------
class TestScriptedFaults:
    def teardown_method(self):
        faults.reset()

    def test_nth_trigger_fires_exactly_once(self):
        faults.configure("prefetch.pull:io_error:@3")
        hits = 0
        for _ in range(6):
            try:
                faults.fire("prefetch.pull")
            except OSError:
                hits += 1
        assert hits == 1
        assert faults.triggered_total() == 1

    def test_nth_replays_on_rearm(self):
        for _ in range(2):
            faults.configure("node.preempt:delay:@2")
            fired_at = []
            for i in range(4):
                before = faults.triggered_total()
                faults.fire("node.preempt")
                if faults.triggered_total() > before:
                    fired_at.append(i)
            assert fired_at == [1]
            faults.reset()

    def test_kill_kind_and_new_sites_parse(self):
        for spec in (
            "node.preempt:kill:@7",
            "rpc.recv:io_error:0.5:3",
            "rendezvous.join:kill:1.0",
        ):
            parsed = faults.FaultSpec.parse(spec)
            assert parsed.site in faults.FAULT_SITES

    def test_bad_nth_rejected(self):
        with pytest.raises(ValueError):
            faults.FaultSpec.parse("node.preempt:kill:@0")
        with pytest.raises(ValueError):
            faults.FaultSpec.parse("node.preempt:kill:@x")


# ---------------------------------------------------------------------------
# watchdog suppression (deliberate drain/resize windows)
# ---------------------------------------------------------------------------
class TestWatchdogSuppression:
    def _recorder(self, tmp_path):
        tr = SpanTracer(enabled=True)
        rec = FlightRecorder(
            base_dir=str(tmp_path),
            tracer=tr,
            registry=MetricsRegistry(),
        )
        return tr, rec

    def test_suppressed_window_blocks_hang_dump(self, tmp_path):
        tr, rec = self._recorder(tmp_path)
        sp = tr.span("ckpt_commit")
        sp.start_ns -= 200_000_000_000  # fake a 200s-old wedge
        rec.suppress_watchdog(30.0)
        try:
            rec.start_watchdog(hang_dump_after_s=60, interval_s=0.02)
            time.sleep(0.2)
            assert rec.dumps == []  # deliberate stall: no forensics
            # window over: the still-open span IS a hang now
            rec.clear_suppression()
            deadline = time.time() + 2
            while time.time() < deadline and not rec.dumps:
                time.sleep(0.02)
            assert len(rec.dumps) == 1
        finally:
            rec.stop_watchdog()
            sp.end()

    def test_windows_extend_never_shrink(self, tmp_path):
        _, rec = self._recorder(tmp_path)
        rec.suppress_watchdog(60.0)
        rec.suppress_watchdog(1.0)  # shorter: must not shrink
        assert rec.watchdog_suppressed()
        until = rec._suppress_until
        assert until >= time.monotonic() + 55


# ---------------------------------------------------------------------------
# telemetry maintenance window (master side of satellite 2)
# ---------------------------------------------------------------------------
class TestMaintenanceWindow:
    def _loaded_aggregator(self):
        agg = TelemetryAggregator(straggler_ratio=2.0, min_samples=4)
        for w, ms in ((0, 100.0), (1, 100.0), (2, 900.0)):
            for _ in range(6):
                agg.observe_metrics(w, 10, {"step_time_ms": ms})
        return agg

    def test_no_new_flags_during_maintenance(self):
        agg = self._loaded_aggregator()
        agg.note_maintenance(30.0)
        assert agg.in_maintenance()
        assert agg.detect_stragglers() == []  # worker 2 NOT minted

    def test_flags_resume_after_window(self):
        agg = self._loaded_aggregator()
        agg.note_maintenance(0.0)  # instantly expired
        assert not agg.in_maintenance()
        assert agg.detect_stragglers() == [2]

    def test_scale_to_opens_window(self):
        agg = self._loaded_aggregator()
        jm = JobManager()
        jm.create_initial_nodes(2)
        scaler = JobAutoScaler(jm, target_nodes=2, telemetry=agg)
        scaler.scale_to(4)
        assert agg.in_maintenance()


# ---------------------------------------------------------------------------
# master: eviction notice -> scheduled departure
# ---------------------------------------------------------------------------
class TestMasterEviction:
    def test_notice_marks_node_and_fires_listeners(self):
        jm = JobManager()
        jm.create_initial_nodes(2)
        seen = []
        jm.add_eviction_listener(
            lambda nt, nid, grace, drain: seen.append((nid, grace))
        )
        jm.handle_eviction_notice(
            "worker", 1, grace_s=25.0, reason="sigterm"
        )
        assert jm.get_node("worker", 1).evicting is True
        assert seen == [(1, 25.0)]
        events = jm.node_events("eviction")
        assert len(events) == 1
        assert "grace=25.0s" in events[0]["detail"]

    def test_announced_death_burns_no_relaunch_budget(self):
        jm = JobManager()
        jm.create_initial_nodes(1)
        brain_events = []
        jm._brain_reporter = (
            lambda nid, host, ev, mem, detail="": brain_events.append(ev)
        )
        jm.handle_eviction_notice("worker", 0, grace_s=10.0)
        node = jm.get_node("worker", 0)
        node.hostname = "spot-host-1"
        failed = Node("worker", 0)
        failed.status = NodeStatus.FAILED
        jm.process_event(NodeEvent("MODIFIED", failed))
        # the replacement exists and kept the budget
        replacement = [
            n
            for n in jm.get_nodes("worker")
            if n.id != 0 and n.rank_index == 0
        ]
        assert len(replacement) == 1
        assert replacement[0].relaunch_count == 0  # not burned
        assert node.exit_reason == NodeExitReason.PREEMPTED
        # the Brain mirror runs fire-and-forget on a daemon thread
        deadline = time.time() + 5
        while "eviction_exit" not in brain_events and time.time() < deadline:
            time.sleep(0.01)
        assert "eviction_exit" in brain_events

    def test_preempted_exhausted_budget_still_relaunches(self):
        jm = JobManager()
        jm.create_initial_nodes(1)
        node = jm.get_node("worker", 0)
        node.relaunch_count = node.max_relaunch_count  # spent
        node.evicting = True
        node.update_status(NodeStatus.FAILED)
        jm._handle_node_failure(node)
        assert any(
            n.id != 0 and n.rank_index == 0
            for n in jm.get_nodes("worker")
        )

    def test_heartbeat_timeout_of_evicting_node_is_preempted(self):
        jm = JobManager()
        jm.create_initial_nodes(2)
        for n in jm.get_nodes("worker"):
            n.update_status(NodeStatus.RUNNING)
            n.heartbeat_time = time.time()
        scaler = JobAutoScaler(jm, target_nodes=2)
        jm.handle_eviction_notice("worker", 1, grace_s=5.0)
        dead = jm.get_node("worker", 1)
        dead.heartbeat_time = time.time() - 10_000
        plan = scaler.check_and_scale()
        assert dead in plan.remove_nodes
        assert dead.exit_reason == NodeExitReason.PREEMPTED
        # the replacement came back with a FRESH budget (PREEMPTED is
        # deliberate, like SCALED_DOWN)
        new = [n for n in plan.launch_nodes if n.rank_index == 1]
        assert len(new) == 1 and new[0].relaunch_count == 0

    def test_servicer_dispatches_eviction_notice(self):
        jm = JobManager()
        jm.create_initial_nodes(1)
        servicer = MasterServicer(job_manager=jm)
        req = comm.BaseRequest(
            node_id=0,
            node_type="worker",
            data=comm.serialize_message(
                comm.EvictionNotice(
                    node_id=0, grace_s=12.0, reason="platform"
                )
            ),
        )
        resp = comm.deserialize_message(
            servicer.report(comm.serialize_message(req))
        )
        assert resp.success
        assert jm.get_node("worker", 0).evicting is True

    def test_prearm_jumps_candidate_queue(self):
        jm = JobManager()
        jm.create_initial_nodes(4)
        pcs = ParalConfigService()
        scaler = JobAutoScaler(
            jm, target_nodes=4, paral_config_service=pcs
        )
        scaler.note_eviction(2, grace_s=20.0)
        cands = scaler.predicted_scale_candidates()
        assert cands[0] == 3  # target - unit leads the queue
        # and it was PUBLISHED immediately, not on the next tick
        cfg = pcs.get_config(0)
        assert list(cfg.candidate_worker_counts)[0] == 3
        assert jm.get_node("worker", 2).evicting is True

    def test_prearm_expires(self):
        jm = JobManager()
        jm.create_initial_nodes(4)
        scaler = JobAutoScaler(jm, target_nodes=4)
        scaler.note_eviction(0, grace_s=20.0)
        scaler._prearm = (scaler._prearm[0], time.monotonic() - 1)
        assert scaler.predicted_scale_candidates()[0] != 3 or (
            scaler._prearm is None
        )


# ---------------------------------------------------------------------------
# rendezvous exclusion
# ---------------------------------------------------------------------------
class TestRendezvousExclusion:
    def _mgr(self, min_nodes=2, max_nodes=3):
        mgr = RendezvousManager("test")
        mgr.update_rdzv_params(min_nodes, max_nodes, 0.0, 1)
        return mgr

    def test_excluded_rank_never_joins_world(self):
        mgr = self._mgr()
        mgr.exclude_node(2, ttl_s=60.0)
        for r in (0, 1, 2):
            mgr.join_rendezvous(r, 1)
        rnd, _, world, _ = mgr.get_comm_world(0)
        assert sorted(world) == [0, 1]
        assert 2 not in world

    def test_exclusion_armed_after_join_purges(self):
        mgr = self._mgr()
        for r in (0, 1, 2):
            mgr.join_rendezvous(r, 1)
        mgr.exclude_node(2, ttl_s=60.0)
        _, _, world, _ = mgr.get_comm_world(0)
        assert sorted(world) == [0, 1]

    def test_exclusion_expires_for_replacement(self):
        mgr = self._mgr()
        mgr.exclude_node(1, ttl_s=0.01)
        time.sleep(0.05)
        for r in (0, 1):
            mgr.join_rendezvous(r, 1)
        _, _, world, _ = mgr.get_comm_world(0)
        assert sorted(world) == [0, 1]
        assert mgr.excluded_ranks() == []

    def test_clear_exclusion(self):
        mgr = self._mgr()
        mgr.exclude_node(0, ttl_s=60.0)
        mgr.clear_exclusion(0)
        for r in (0, 1):
            mgr.join_rendezvous(r, 1)
        _, _, world, _ = mgr.get_comm_world(0)
        assert sorted(world) == [0, 1]

    def test_relaunch_clears_exclusion_for_replacement(self):
        """The healthy replacement inherits the dead node's rank — it
        must not sit out the exclusion TTL. Covers BOTH comeback
        paths: the event relaunch and the auto-scaler replacement."""
        from dlrover_tpu.master.local_master import LocalJobMaster

        master = LocalJobMaster(node_num=2)  # never prepare()d
        master.job_manager.handle_eviction_notice(
            "worker", 1, grace_s=30.0
        )
        rdzv = list(master.rdzv_managers.values())[0]
        assert rdzv.excluded_ranks() == [1]
        # path 1: event relaunch
        node = master.job_manager.get_node("worker", 1)
        node.update_status(NodeStatus.FAILED)
        master.job_manager._handle_node_failure(node)
        assert rdzv.excluded_ranks() == []
        # path 2: auto-scaler replacement creation
        master.job_manager.handle_eviction_notice(
            "worker", 0, grace_s=30.0
        )
        assert rdzv.excluded_ranks() == [0]
        dead = master.job_manager.get_node("worker", 0)
        dead.is_released = True
        dead.update_status(NodeStatus.FAILED)
        master.auto_scaler.check_and_scale()
        assert rdzv.excluded_ranks() == []

    def test_evict_worker_rounds_grace_up(self):
        from dlrover_tpu.master.local_master import LocalJobMaster

        master = LocalJobMaster(node_num=1)
        master.evict_worker(0, grace_s=0.9)
        cmds = master.servicer._worker_commands[0]
        evicts = [c for c in cmds if c.kind == "evict"]
        # int() would yield arg=0 = "use the 30s default" against a
        # sub-second platform kill; ceil keeps the window honest
        assert evicts and evicts[-1].arg == 1


# ---------------------------------------------------------------------------
# monitor relay: metrics file -> EvictionNotice RPC
# ---------------------------------------------------------------------------
class TestMonitorRelay:
    def test_training_monitor_forwards_notice_once(
        self, tmp_path, monkeypatch
    ):
        from dlrover_tpu.agent.monitor import (
            TrainingMonitor,
            report_runtime_metrics,
        )

        path = str(tmp_path / "metrics.json")
        monkeypatch.setenv("DLROVER_TPU_RUNTIME_METRICS_PATH", path)

        class _Client:
            def __init__(self):
                self.notices = []

            def report_eviction_notice(self, grace, drain_ms=0.0,
                                       reason=""):
                self.notices.append((grace, drain_ms))

            def report_global_step(self, step):
                pass

            def report_train_metrics(self, *a, **kw):
                pass

        client = _Client()
        mon = TrainingMonitor(client, interval=1000)
        report_runtime_metrics(
            5, eviction_pending=1.0, eviction_grace_s=20.0
        )
        mon._tick()
        mon._tick()  # unchanged: no duplicate notice
        assert client.notices == [(20.0, 0.0)]
        # the drain's final write adds the measured latency
        report_runtime_metrics(
            5,
            eviction_pending=1.0,
            eviction_grace_s=20.0,
            eviction_drain_ms=412.0,
        )
        mon._tick()
        assert client.notices == [(20.0, 0.0), (20.0, 412.0)]


# ---------------------------------------------------------------------------
# Brain: eviction-aware floors + drain-latency-priced dwell
# ---------------------------------------------------------------------------
class TestBrainEvictionPricing:
    def _store_with_job(self, job, sizes=((4, 1.0), (8, 1.6))):
        from dlrover_tpu.brain.service import BrainServicer

        ds = BrainServicer(db_path=":memory:")
        for n, sps in sizes:
            for _ in range(3):
                ds.persist_metrics(
                    job,
                    comm.JobMetricsSample(
                        timestamp=time.time(),
                        global_step=100,
                        steps_per_sec=sps,
                        alive_nodes=n,
                        goodput_pct=90.0,
                    ),
                )
        return ds

    def test_parse_drain_ms(self):
        from dlrover_tpu.brain.scheduler import parse_drain_ms

        assert parse_drain_ms("grace=20.0s drain_ms=412 x") == 412.0
        assert parse_drain_ms("grace=20.0s") == 0.0
        assert parse_drain_ms("drain_ms=oops") == 0.0
        assert parse_drain_ms("") == 0.0

    def test_detail_column_round_trip_and_migration(self, tmp_path):
        import sqlite3

        from dlrover_tpu.brain.service import BrainServicer

        # a pre-eviction store: node_events WITHOUT the detail column
        db = str(tmp_path / "old.db")
        conn = sqlite3.connect(db)
        conn.execute(
            "CREATE TABLE node_events (job TEXT NOT NULL, ts REAL NOT "
            "NULL, node_id INTEGER, hostname TEXT, event TEXT NOT "
            "NULL, memory_mb INTEGER, cpu_percent REAL)"
        )
        conn.execute(
            "INSERT INTO node_events VALUES "
            "('legacy', ?, 0, 'h', 'oom', 512, 0.0)",
            (time.time(),),  # recent: the retention prune keeps it
        )
        conn.commit()
        conn.close()
        ds = BrainServicer(db_path=db)
        ds.record_node_event(
            comm.BrainNodeEventReport(
                job_name="j1",
                node_id=0,
                hostname="spot-1",
                event="eviction",
                detail="grace=20.0s drain_ms=300",
            )
        )
        rows = ds.node_events(job="j1", event="eviction")
        assert rows[0].detail == "grace=20.0s drain_ms=300"
        legacy = ds.node_events(job="legacy")
        assert legacy[0].detail == ""

    def test_eviction_raises_floor(self):
        from dlrover_tpu.brain.scheduler import ClusterScheduler

        job = "spotty"
        ds = self._store_with_job(job)
        sched = ClusterScheduler(ds, total_chips=16, node_unit=1)
        base = sched.job_state(job, time.time()).floor
        ds.record_node_event(
            comm.BrainNodeEventReport(
                job_name=job,
                node_id=0,
                hostname="spot-1",
                event="eviction",
                detail="grace=20.0s drain_ms=250",
            )
        )
        st = sched.job_state(job, time.time())
        assert st.floor == base + sched.node_unit
        assert "eviction_prone" in st.verdicts

    def test_dwell_priced_from_measured_downtime(self):
        from dlrover_tpu.brain.scheduler import (
            DWELL_DOWNTIME_FACTOR,
            ClusterScheduler,
        )

        job = "heavy-resize"
        ds = self._store_with_job(job)
        sched = ClusterScheduler(
            ds, total_chips=16, node_unit=1, min_dwell_s=10.0
        )
        now = time.time()
        assert sched.dwell_for(job, now) == 10.0  # nothing measured
        # a measured 4 s decision->resized latency prices the dwell
        ds.record_cluster_plan(
            ds.next_plan_version(),
            [{"job": job, "worker_count": 8, "prev_count": 4,
              "reason": "t", "exclude_hosts": []}],
            now,
        )
        ds.record_plan_outcome(
            comm.PlanOutcomeReport(
                job_name=job,
                version=ds.latest_plan_version(),
                worker_count=8,
                decision_to_resized_ms=4000.0,
            )
        )
        assert sched.dwell_for(job, now) == pytest.approx(
            DWELL_DOWNTIME_FACTOR * 4.0
        )
        # an eviction drain stacks on top (the job pays both per move)
        ds.record_node_event(
            comm.BrainNodeEventReport(
                job_name=job, node_id=0, hostname="h",
                event="eviction", detail="drain_ms=2000",
            )
        )
        assert sched.dwell_for(job, now) == pytest.approx(
            DWELL_DOWNTIME_FACTOR * 6.0
        )

    def test_cheap_resizer_keeps_floor_dwell(self):
        from dlrover_tpu.brain.scheduler import ClusterScheduler

        job = "warm-dp"
        ds = self._store_with_job(job)
        sched = ClusterScheduler(
            ds, total_chips=16, node_unit=1, min_dwell_s=120.0
        )
        now = time.time()
        ds.record_cluster_plan(
            ds.next_plan_version(),
            [{"job": job, "worker_count": 8, "prev_count": 4,
              "reason": "t", "exclude_hosts": []}],
            now,
        )
        ds.record_plan_outcome(
            comm.PlanOutcomeReport(
                job_name=job,
                version=ds.latest_plan_version(),
                worker_count=8,
                decision_to_resized_ms=200.0,  # 0.2 s warm resize
            )
        )
        assert sched.dwell_for(job, now) == 120.0


# ---------------------------------------------------------------------------
# worker drain, end to end on a real tiny trainer
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def drained_trainer(tmp_path_factory):
    """One trainer evicted mid-run; every drain contract asserts off
    this single (expensive) run."""
    import jax
    import optax

    from dlrover_tpu.accel.strategy import Strategy
    from dlrover_tpu.models import tiny
    from dlrover_tpu.parallel.mesh import MeshConfig
    from dlrover_tpu.trainer.elastic.trainer import (
        ElasticTrainer,
        TrainerConfig,
    )

    tmp = tmp_path_factory.mktemp("evict_run")
    metrics_path = str(tmp / "runtime_metrics.json")
    flight_dir = str(tmp / "flight")
    old_m = os.environ.get("DLROVER_TPU_RUNTIME_METRICS_PATH")
    old_f = os.environ.get("DLROVER_TPU_FLIGHT_DIR")
    os.environ["DLROVER_TPU_RUNTIME_METRICS_PATH"] = metrics_path
    os.environ["DLROVER_TPU_FLIGHT_DIR"] = flight_dir

    class _Tokens:
        def __init__(self, n=256, seq=32, vocab=256):
            rng = np.random.default_rng(3)
            self.data = rng.integers(
                0, vocab, (n, seq + 1), dtype=np.int32
            )

        def __len__(self):
            return len(self.data)

        def __getitem__(self, i):
            return {"x": self.data[i][:-1], "y": self.data[i][1:]}

    events = []
    trainer = ElasticTrainer(
        model_cfg=tiny(num_layers=1),
        tx=optax.adamw(1e-2),
        dataset=_Tokens(),
        trainer_cfg=TrainerConfig(
            batch_size=8,
            seq_len=32,
            ckpt_dir=str(tmp / "ckpt"),
            save_memory_interval=4,
            save_storage_interval=10_000,
            report_metrics=True,
            log_interval=4,
            prefetch=2,
            donation_aware=False,
            speculative_compile=False,
            eviction_grace_s=20.0,
        ),
        strategy=Strategy(mesh=MeshConfig(dp=1), dtype="float32"),
        devices=list(jax.devices())[:1],
        metrics_hook=lambda step, m: (
            trainer.request_eviction(20.0, reason="test")
            if step == 6
            else None
        ),
    )
    trainer.set_event_reporter(
        lambda ev, detail: events.append((ev, detail))
    )
    try:
        trainer.train(12)
        yield {
            "trainer": trainer,
            "events": events,
            "metrics_path": metrics_path,
            "flight_dir": flight_dir,
            "ckpt_dir": str(tmp / "ckpt"),
        }
    finally:
        # the drain suppressed the PROCESS-DEFAULT recorder's watchdog
        # for the grace window; later test files share that recorder
        trainer._flight.clear_suppression()
        trainer.close()
        for key, old in (
            ("DLROVER_TPU_RUNTIME_METRICS_PATH", old_m),
            ("DLROVER_TPU_FLIGHT_DIR", old_f),
        ):
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old


class TestDrainStateMachine:
    def test_drain_stops_training_at_notice(self, drained_trainer):
        t = drained_trainer["trainer"]
        assert t.evicted is True
        assert t.global_step == 6  # finished the in-flight step, no more

    def test_emergency_checkpoint_is_verified_and_current(
        self, drained_trainer
    ):
        t = drained_trainer["trainer"]
        assert t._ckptr.latest_verified_step() == 6

    def test_drain_booked_as_eviction_goodput(self, drained_trainer):
        t = drained_trainer["trainer"]
        rep = t._goodput.snapshot()
        assert rep.seconds["eviction"] > 0
        assert t.eviction_drain_ms > 0

    def test_event_reporter_saw_notice_and_drain(self, drained_trainer):
        events = drained_trainer["events"]
        assert len(events) >= 2
        assert all(ev == "eviction" for ev, _ in events)
        assert any("drain_ms=" in d for _, d in events)

    def test_final_metrics_carry_drain_latency(self, drained_trainer):
        with open(drained_trainer["metrics_path"]) as f:
            metrics = json.load(f)
        assert metrics["eviction_pending"] == 1.0
        assert metrics["eviction_grace_s"] == 20.0
        assert metrics["eviction_drain_ms"] > 0

    def test_flight_bundle_dumped(self, drained_trainer):
        d = drained_trainer["flight_dir"]
        assert os.path.isdir(d)
        assert any("eviction" in name for name in os.listdir(d))

    def test_watchdog_suppressed_through_drain(self, drained_trainer):
        t = drained_trainer["trainer"]
        assert t._flight.watchdog_suppressed()

    def test_evict_worker_command_requests_drain(
        self, tmp_path, monkeypatch, drained_trainer
    ):
        """The PR-7 command channel leg: an `evict` command in the
        relay file arms the drain with the master's grace window."""
        from dlrover_tpu.agent.monitor import atomic_write_json

        t = drained_trainer["trainer"]
        path = str(tmp_path / "commands.json")
        monkeypatch.setenv("DLROVER_TPU_WORKER_COMMANDS_PATH", path)
        atomic_write_json(
            path,
            {
                "commands": [
                    {
                        "id": t._last_command_id + 1,
                        "kind": "evict",
                        "arg": 7,
                        "reason": "operator",
                    }
                ]
            },
        )
        # reset the (already drained) eviction state to observe arming
        t.evicted = False
        t._evict_event.clear()
        t._evict_deadline = None
        t._poll_worker_commands()
        assert t.eviction_pending
        assert t._evict_grace_s == 7.0
        assert "master_operator" in t._evict_reason


# ---------------------------------------------------------------------------
# rpc.recv fault site (satellite 1): response-leg retry coverage
# ---------------------------------------------------------------------------
class TestRpcRecvFaultSite:
    def teardown_method(self):
        faults.reset()

    def _client(self):
        from dlrover_tpu.agent.master_client import MasterClient

        c = MasterClient.__new__(MasterClient)
        c._master_addr = "test:0"
        c._node_id = 0
        c._node_type = "worker"
        c._timeout = 1.0
        return c

    def test_recv_leg_failure_rides_jittered_retry(self, monkeypatch):
        """The server APPLIED the request but the response leg died:
        the jittered-retry path must resend and succeed — rpc.recv
        coverage, not just rpc.send."""
        import dlrover_tpu.agent.master_client as mc

        client = self._client()
        calls = {"n": 0}
        ok = comm.BaseResponse(
            data=comm.serialize_message(comm.SyncResult(done=True))
        )

        def fake_rpc(payload, timeout=None):
            calls["n"] += 1
            return comm.serialize_message(ok)

        sleeps = []
        monkeypatch.setattr(
            mc.time, "sleep", lambda s: sleeps.append(s)
        )
        faults.configure("rpc.recv:io_error:@1")
        resp = client._call(fake_rpc, comm.SyncResult())
        assert resp.done is True
        # the rpc itself ran twice: the first RESPONSE was eaten after
        # the server had already processed the request
        assert calls["n"] == 2
        assert len(sleeps) == 1
        assert faults.triggered() == {("rpc.recv", "io_error"): 1}

    def test_recv_leg_single_attempt_for_non_idempotent(
        self, monkeypatch
    ):
        """A non-idempotent report must NOT retry past a lost
        response — replay would double-apply server-side."""
        import dlrover_tpu.agent.master_client as mc

        client = self._client()
        client._report_rpc = lambda payload, timeout=None: (
            comm.serialize_message(comm.BaseResponse())
        )
        calls = {"n": 0}

        def fake_rpc(payload, timeout=None):
            calls["n"] += 1
            return comm.serialize_message(comm.BaseResponse())

        client._report_rpc = fake_rpc
        monkeypatch.setattr(mc.time, "sleep", lambda s: None)
        faults.configure("rpc.recv:io_error:1.0")
        with pytest.raises(ConnectionError):
            client.report(comm.KeyValueAdd(key="k", amount=1),
                          idempotent=False)
        assert calls["n"] == 1
