"""Deterministic chaos matrix: fault injection + checkpoint integrity.

The random-SIGKILL soak (test_chaos_soak.py, slow tier) only exercises
process death. This file is the deterministic tier-1 matrix for the
storage/RPC failure scenarios: every registered checkpoint fault point
is armed (torn write / bit flip / ENOSPC / IO error), and the contract
under test is always the same — corruption is DETECTED at load, restore
falls back to the newest *verified* step, training resumes from it, and
a corrupt newest step is never silently restored. Plus: degraded
(shm-only) checkpoint mode on persistent ENOSPC, saver fast-fail on a
dead shard thread, retry hardening of the master client, and the
prefetch/reshard fault sites.
"""

import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.common import faults
from dlrover_tpu.common.storage import PosixDiskStorage
from dlrover_tpu.ckpt import saver as saver_mod
from dlrover_tpu.ckpt.checkpointer import FlashCheckpointer, StorageType
from dlrover_tpu.ckpt.engine import CheckpointEngine
from dlrover_tpu.ckpt.saver import (
    AsyncCheckpointSaver,
    gc_checkpoints,
    quarantine_step_dir,
    read_history,
    read_tracker,
    resolve_verified_step,
    shard_file,
    step_dir,
    verify_step_dir,
)


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends with no fault armed and zero tallies."""
    faults.reset()
    yield
    faults.reset()


@pytest.fixture
def saver(tmp_path):
    AsyncCheckpointSaver.reset()
    s = AsyncCheckpointSaver.start_async_saving_ckpt(local_shard_num=1)
    # keep the retry/backoff dance fast for tests
    s.persist_retries = 2
    s.persist_backoff_base = 0.01
    s.persist_backoff_cap = 0.02
    yield s
    AsyncCheckpointSaver.reset()


# ---------------------------------------------------------------------------
# fault framework
# ---------------------------------------------------------------------------
class TestFaultSpecs:
    def test_parse_full(self):
        s = faults.FaultSpec.parse("ckpt.shard_write:torn_write:0.5:42")
        assert s.site == "ckpt.shard_write"
        assert s.kind == "torn_write"
        assert s.prob == 0.5
        assert s.seed == 42

    def test_parse_derives_stable_seed(self):
        a = faults.FaultSpec.parse("ckpt.persist:enospc:1.0")
        b = faults.FaultSpec.parse("ckpt.persist:enospc:1.0")
        assert a.seed == b.seed

    @pytest.mark.parametrize(
        "raw",
        [
            "nope.site:enospc:1.0",  # unknown site
            "ckpt.persist:frobnicate:1.0",  # unknown kind
            "ckpt.persist:enospc:2.0",  # prob out of range
            "ckpt.persist:enospc",  # missing prob
            "ckpt.persist:enospc:xyz",  # unparsable prob
        ],
    )
    def test_parse_rejects(self, raw):
        with pytest.raises(ValueError):
            faults.FaultSpec.parse(raw)

    def test_seeded_triggering_is_deterministic(self):
        def run():
            inj = faults.FaultInjector()
            inj.configure("ckpt.persist:enospc:0.5:7")
            seq = []
            for _ in range(32):
                try:
                    inj.fire("ckpt.persist")
                    seq.append(0)
                except OSError:
                    seq.append(1)
            return seq

        a, b = run(), run()
        assert a == b, "same spec+seed must replay the same sequence"
        assert 0 < sum(a) < 32, "prob 0.5 should mix hits and misses"

    def test_env_activation_and_reload(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "ckpt.persist:enospc:1.0")
        faults.reload_from_env()
        with pytest.raises(OSError) as ei:
            faults.fire("ckpt.persist")
        import errno

        assert ei.value.errno == errno.ENOSPC
        monkeypatch.delenv(faults.ENV_VAR)
        faults.reload_from_env()
        faults.fire("ckpt.persist")  # disarmed: no-op

    def test_wildcard_site_and_tally(self):
        faults.configure("*:io_error:1.0")
        for site in ("rpc.send", "prefetch.pull"):
            with pytest.raises(OSError):
                faults.fire(site)
        t = faults.triggered()
        assert t[("rpc.send", "io_error")] == 1
        assert t[("prefetch.pull", "io_error")] == 1
        assert faults.triggered_total() == 2

    def test_triggered_counts_into_metrics_registry(self):
        from dlrover_tpu.obs.metrics import default_registry

        c = default_registry().counter(
            "dlrover_faults_triggered_total",
            "injected faults that fired, by site and kind",
            labelnames=("site", "kind"),
        )
        before = c.labels("ckpt.persist", "delay").value
        faults.configure("ckpt.persist:delay:1.0")
        faults.fire("ckpt.persist")
        assert c.labels("ckpt.persist", "delay").value == before + 1

    def test_corrupt_torn_write_truncates(self):
        faults.configure("ckpt.shard_write:torn_write:1.0:3")
        blob = bytes(range(256)) * 8
        out = faults.corrupt("ckpt.shard_write", blob)
        assert 0 < len(out) < len(blob)
        assert out == blob[: len(out)]

    def test_corrupt_bit_flip_changes_one_bit(self):
        faults.configure("ckpt.shard_write:bit_flip:1.0:3")
        blob = b"\x00" * 64
        out = faults.corrupt("ckpt.shard_write", blob)
        assert len(out) == len(blob)
        diff = [a ^ b for a, b in zip(blob, out)]
        assert sum(bin(d).count("1") for d in diff) == 1

    def test_corrupt_array_keeps_length(self):
        faults.configure("ckpt.shm_stage:bit_flip:1.0:5")
        arr = np.ones(16, np.float32)
        out = faults.corrupt_array("ckpt.shm_stage", arr)
        assert out.nbytes == arr.nbytes
        assert not np.array_equal(
            np.asarray(out).view(np.uint8),
            np.ascontiguousarray(arr).view(np.uint8),
        )

    def test_inactive_paths_are_noops(self):
        faults.fire("ckpt.persist")
        assert faults.corrupt("ckpt.shard_write", b"abc") == b"abc"
        arr = np.arange(4.0)
        assert faults.corrupt_array("ckpt.shm_stage", arr) is arr

    def test_corrupt_array_scale_is_finite_but_wrong(self):
        # the SDC kind: a deterministic slice multiplied by a large
        # factor — wrong numbers that every finite fence passes
        faults.configure("device.sdc:scale:1.0:7")
        arr = np.ones(64, np.float32)
        out = np.asarray(faults.corrupt_array("device.sdc", arr))
        assert out.shape == arr.shape
        assert np.all(np.isfinite(out))
        scaled = int(np.sum(out == np.float32(faults.SCALE_FACTOR)))
        assert scaled == 64 // 8  # an eighth of the elements
        assert int(np.sum(out == 1.0)) == 64 - scaled

    def test_corrupt_array_scale_is_seed_deterministic(self):
        arr = np.arange(1, 65, dtype=np.float32)
        faults.configure("device.sdc:scale:1.0:7")
        a = np.asarray(faults.corrupt_array("device.sdc", arr.copy()))
        faults.reset()
        faults.configure("device.sdc:scale:1.0:7")
        b = np.asarray(faults.corrupt_array("device.sdc", arr.copy()))
        assert np.array_equal(a, b)

    def test_corrupt_bytes_ignores_scale_kind(self):
        # bytes carry no dtype to scale: the data kind must act only at
        # array sites, never rot a byte stream it cannot interpret
        faults.configure("device.sdc:scale:1.0:7")
        blob = bytes(range(64))
        assert faults.corrupt("device.sdc", blob) == blob


# ---------------------------------------------------------------------------
# step-dir integrity primitives
# ---------------------------------------------------------------------------
def _write_step(storage, ckpt_dir, step, value=1.0):
    """One shard of a tiny state persisted through the production
    helpers (payload + crc + done file)."""
    from dlrover_tpu.ckpt.sharding import host_shard_records

    records = host_shard_records(
        {"w": np.full(8, value, np.float32), "step": step}
    )
    storage.safe_makedirs(
        os.path.join(step_dir(ckpt_dir, step), saver_mod.DONE_DIR)
    )
    payload = saver_mod.build_shard_payload(step, 0, 1, records, {})
    saver_mod.write_shard_and_done(storage, ckpt_dir, step, payload)
    saver_mod.commit_checkpoint(storage, ckpt_dir, step, 1, timeout=5)


class TestStepVerification:
    def test_clean_step_verifies(self, tmp_path):
        st = PosixDiskStorage()
        _write_step(st, str(tmp_path), 3)
        ok, reason = verify_step_dir(st, str(tmp_path), 3)
        assert ok, reason

    def test_torn_shard_detected(self, tmp_path):
        st = PosixDiskStorage()
        _write_step(st, str(tmp_path), 3)
        path = shard_file(str(tmp_path), 3, 0)
        blob = open(path, "rb").read()
        open(path, "wb").write(blob[: len(blob) // 2])
        ok, reason = verify_step_dir(st, str(tmp_path), 3)
        assert not ok and "torn" in reason

    def test_bit_flip_detected(self, tmp_path):
        st = PosixDiskStorage()
        _write_step(st, str(tmp_path), 3)
        path = shard_file(str(tmp_path), 3, 0)
        blob = bytearray(open(path, "rb").read())
        blob[len(blob) // 2] ^= 0x10
        open(path, "wb").write(bytes(blob))
        ok, reason = verify_step_dir(st, str(tmp_path), 3)
        assert not ok and "checksum" in reason

    def test_missing_done_file_detected(self, tmp_path):
        st = PosixDiskStorage()
        _write_step(st, str(tmp_path), 3)
        os.remove(
            os.path.join(
                step_dir(str(tmp_path), 3), saver_mod.DONE_DIR, "0.done"
            )
        )
        ok, reason = verify_step_dir(st, str(tmp_path), 3)
        assert not ok

    def test_missing_shard_of_advertised_set_detected(self, tmp_path):
        st = PosixDiskStorage()
        _write_step(st, str(tmp_path), 3)
        # done file advertises 2 global shards but only shard 0 exists
        done = os.path.join(
            step_dir(str(tmp_path), 3), saver_mod.DONE_DIR, "0.done"
        )
        meta = saver_mod.parse_done(open(done).read())
        meta["global_shard_num"] = 2
        import json

        open(done, "w").write(json.dumps(meta))
        ok, reason = verify_step_dir(st, str(tmp_path), 3)
        assert not ok and "partial" in reason

    def test_legacy_bare_int_done_file_still_verifies(self, tmp_path):
        st = PosixDiskStorage()
        _write_step(st, str(tmp_path), 3)
        done = os.path.join(
            step_dir(str(tmp_path), 3), saver_mod.DONE_DIR, "0.done"
        )
        open(done, "w").write("1")  # pre-checksum format: shard count
        ok, reason = verify_step_dir(st, str(tmp_path), 3)
        assert ok, reason

    def test_quarantine_moves_dir_out_of_restore_path(self, tmp_path):
        st = PosixDiskStorage()
        _write_step(st, str(tmp_path), 3)
        q = quarantine_step_dir(st, str(tmp_path), 3)
        assert q and q.endswith(".corrupt")
        assert not os.path.exists(step_dir(str(tmp_path), 3))
        assert os.path.exists(q)

    def test_rollback_to_newest_verified(self, tmp_path):
        st = PosixDiskStorage()
        for s in (1, 2, 3):
            _write_step(st, str(tmp_path), s)
        # corrupt the newest two
        for s in (2, 3):
            path = shard_file(str(tmp_path), s, 0)
            open(path, "ab").write(b"xx")  # length mismatch
        good = resolve_verified_step(st, str(tmp_path))
        assert good == 1
        assert read_tracker(st, str(tmp_path)) == 1
        assert read_history(st, str(tmp_path)) == [1]
        # both bad dirs quarantined
        names = os.listdir(tmp_path)
        assert sum(".corrupt" in n for n in names) == 2

    def test_no_verifiable_checkpoint_clears_tracker(self, tmp_path):
        st = PosixDiskStorage()
        _write_step(st, str(tmp_path), 1)
        open(shard_file(str(tmp_path), 1, 0), "wb").write(b"junk")
        assert resolve_verified_step(st, str(tmp_path)) == -1
        assert read_tracker(st, str(tmp_path)) == -1

    def test_repair_false_never_mutates(self, tmp_path):
        st = PosixDiskStorage()
        for s in (1, 2):
            _write_step(st, str(tmp_path), s)
        open(shard_file(str(tmp_path), 2, 0), "ab").write(b"x")
        assert resolve_verified_step(st, str(tmp_path), repair=False) == 1
        # non-repairing caller (shard id != 0) left everything in place
        assert read_tracker(st, str(tmp_path)) == 2
        assert os.path.exists(step_dir(str(tmp_path), 2))

    def test_history_is_bounded_and_gc_prunes(self, tmp_path):
        st = PosixDiskStorage()
        n = saver_mod.COMMIT_HISTORY_KEEP + 4
        for s in range(1, n + 1):
            _write_step(st, str(tmp_path), s)
        hist = read_history(st, str(tmp_path))
        assert len(hist) <= saver_mod.COMMIT_HISTORY_KEEP
        assert hist[-1] == n
        # commit-time GC dropped the dirs that fell out of the history
        dirs = [
            d for d in os.listdir(tmp_path) if d.startswith("step_")
        ]
        assert len(dirs) <= saver_mod.COMMIT_HISTORY_KEEP

    def test_gc_keeps_quarantine_budget(self, tmp_path):
        st = PosixDiskStorage()
        for s in (1, 2, 3, 4):
            _write_step(st, str(tmp_path), s)
        for s in (1, 2, 3):
            quarantine_step_dir(st, str(tmp_path), s)
        removed = gc_checkpoints(
            st, str(tmp_path), keep_quarantined=1
        )
        assert removed >= 2
        names = os.listdir(tmp_path)
        assert sum(".corrupt" in n for n in names) == 1

    def test_gc_never_touches_steps_newer_than_tracker(self, tmp_path):
        st = PosixDiskStorage()
        _write_step(st, str(tmp_path), 1)
        # an in-flight persist: dir exists, not yet committed
        st.safe_makedirs(step_dir(str(tmp_path), 9))
        gc_checkpoints(st, str(tmp_path), keep_steps=1)
        assert os.path.exists(step_dir(str(tmp_path), 9))

    def test_upgrade_from_tracker_only_keeps_fallback(self, tmp_path):
        """First commit after upgrading from the single-tracker protocol:
        pre-existing step dirs have no history file — GC must seed the
        history from them, not wipe every old step as 'untracked'."""
        st = PosixDiskStorage()
        for s in (1, 2, 3):
            _write_step(st, str(tmp_path), s)
        os.remove(os.path.join(str(tmp_path), saver_mod.HISTORY_FILE))
        _write_step(st, str(tmp_path), 4)  # first post-upgrade commit
        assert os.path.exists(step_dir(str(tmp_path), 3)), (
            "upgrade GC deleted the pre-history fallback step"
        )
        # the exact data-loss scenario: the new step is torn; restore
        # must fall back to a pre-history step, not to nothing
        path = shard_file(str(tmp_path), 4, 0)
        blob = open(path, "rb").read()
        open(path, "wb").write(blob[: len(blob) // 2])
        assert resolve_verified_step(st, str(tmp_path)) == 3

    def test_shallow_verify_lengths_only(self, tmp_path):
        """deep=False (non-repair ranks) checks completeness + lengths
        without reading blobs: torn writes caught, bit flips left to the
        repairing rank's one deep pass."""
        st = PosixDiskStorage()
        _write_step(st, str(tmp_path), 3)
        ok, reason = verify_step_dir(st, str(tmp_path), 3, deep=False)
        assert ok, reason
        path = shard_file(str(tmp_path), 3, 0)
        blob = bytearray(open(path, "rb").read())
        blob[len(blob) // 2] ^= 0x10
        open(path, "wb").write(bytes(blob))
        ok, _ = verify_step_dir(st, str(tmp_path), 3, deep=False)
        assert ok  # same length: shallow cannot see it...
        ok, _ = verify_step_dir(st, str(tmp_path), 3, deep=True)
        assert not ok  # ...the deep pass (repairing rank) does
        open(path, "wb").write(bytes(blob[: len(blob) // 2]))
        ok, reason = verify_step_dir(st, str(tmp_path), 3, deep=False)
        assert not ok and "torn" in reason


# ---------------------------------------------------------------------------
# chaos matrix: end-to-end detect -> rollback -> resume (sync engine path)
# ---------------------------------------------------------------------------
_TARGET = np.linspace(-1.0, 1.0, 8).astype(np.float32)


def _train(w, n):
    """Deterministic toy training (pure float32 SGD on a quadratic):
    bitwise-reproducible, so loss continuity can be asserted exactly."""
    losses = []
    for _ in range(n):
        w = (w - np.float32(0.1) * (w - _TARGET)).astype(np.float32)
        losses.append(float(np.square(w - _TARGET).sum()))
    return w, losses


class TestChaosMatrix:
    """One scenario per registered checkpoint fault point: the injected
    fault is detected, restore falls back to the newest verified step,
    and training resumed from it reproduces the clean run exactly."""

    def _ckptr(self, tmp_path):
        AsyncCheckpointSaver.reset()  # force the sync (no-agent) path
        ckptr = FlashCheckpointer(str(tmp_path / "ckpt"))
        assert not ckptr.engine._agent_mode
        return ckptr

    def _save(self, ckptr, step, w):
        return ckptr.save_checkpoint(
            step, {"w": jnp.asarray(w), "step": step}, StorageType.DISK
        )

    def _run_scenario(self, tmp_path, arm_spec, save2_ok=None):
        """Clean save at step 4; faulted save at step 8; 'crash';
        restore must land on step 4 and retraining must reproduce the
        uninterrupted trajectory."""
        ckptr = self._ckptr(tmp_path)
        w0 = np.zeros(8, np.float32)
        w4, _ = _train(w0, 4)
        assert self._save(ckptr, 4, w4)
        w8_clean, losses_clean = _train(w4, 4)

        faults.configure(arm_spec)
        ok = self._save(ckptr, 8, w8_clean)
        if save2_ok is not None:
            assert ok is save2_ok
        faults.reset()
        assert faults.active() is False

        # "crash + restart": a fresh load must roll back to step 4 —
        # never silently restore a corrupt/unpublished step 8
        target = {"w": jnp.zeros(8, jnp.float32), "step": 0}
        step, state = ckptr.load_checkpoint(target)
        assert step == 4, f"expected rollback to 4, got {step}"
        np.testing.assert_array_equal(np.asarray(state["w"]), w4)

        # loss continuity: resume from the restored state
        _, losses_resumed = _train(
            np.asarray(state["w"], np.float32), 4
        )
        assert losses_resumed == losses_clean
        return ckptr

    def test_shard_write_torn(self, tmp_path):
        ckptr = self._run_scenario(
            tmp_path, "ckpt.shard_write:torn_write:1.0:11", save2_ok=True
        )
        assert faults.triggered() == {}  # reset cleared the tally
        # the corrupt step was quarantined, not deleted silently
        names = os.listdir(ckptr.checkpoint_dir)
        assert any(".corrupt" in n for n in names)

    def test_shard_write_bit_flip(self, tmp_path):
        self._run_scenario(
            tmp_path, "ckpt.shard_write:bit_flip:1.0:12", save2_ok=True
        )

    def test_done_write_io_error(self, tmp_path):
        # crash-between-shard-and-done: shard landed, done never did,
        # step never published -> restore ignores it
        ckptr = self._run_scenario(
            tmp_path, "ckpt.done_write:io_error:1.0", save2_ok=False
        )
        assert read_tracker(
            ckptr.engine.storage, ckptr.checkpoint_dir
        ) == 4

    def test_tracker_write_enospc(self, tmp_path):
        # crash-before-tracker: fully valid step dir, never published
        self._run_scenario(
            tmp_path, "ckpt.tracker_write:enospc:1.0", save2_ok=False
        )

    def test_persist_enospc_training_continues(self, tmp_path):
        # disk full before anything is written: save reports False (the
        # train loop keeps going), previous verified step stays live
        ckptr = self._run_scenario(
            tmp_path, "ckpt.persist:enospc:1.0", save2_ok=False
        )
        # metric visible in the registry
        from dlrover_tpu.obs.metrics import default_registry

        assert (
            default_registry()
            .counter("dlrover_ckpt_persist_failures_total")
            .value
            >= 1
        )
        # the failed save left nothing: a later healthy save commits
        w8, _ = _train(np.zeros(8, np.float32), 8)
        assert self._save(ckptr, 8, w8)
        assert ckptr.latest_verified_step() == 8

    def test_corrupt_newest_never_silently_restores(self, tmp_path):
        """Paranoia variant: BOTH saved steps corrupt -> load must say
        'no checkpoint', not hand back bad bytes."""
        ckptr = self._ckptr(tmp_path)
        faults.configure("ckpt.shard_write:bit_flip:1.0:13")
        for s in (4, 8):
            w, _ = _train(np.zeros(8, np.float32), s)
            assert self._save(ckptr, s, w)
        faults.reset()
        step, state = ckptr.load_checkpoint(
            {"w": jnp.zeros(8, jnp.float32), "step": 0}
        )
        assert step == -1 and state is None


# ---------------------------------------------------------------------------
# agent path: shm corruption, degraded mode, shard-thread fast-fail
# ---------------------------------------------------------------------------
def _wait(pred, timeout=30.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestAgentFaults:
    def test_shm_stage_bit_flip_detected_and_storage_fallback(
        self, saver, tmp_path
    ):
        events = []
        saver.set_event_reporter(lambda ev, msg: events.append((ev, msg)))
        ckpt_dir = str(tmp_path / "ckpt")
        engine = CheckpointEngine()
        assert engine._agent_mode
        state = {"w": jnp.arange(16.0), "step": 1}
        # clean step 1 on storage
        assert engine.save_to_memory(1, state, ckpt_dir)
        assert _wait(lambda: engine.latest_step(ckpt_dir) == 1)

        # step 2 staged through a corrupting shm write: the writer's
        # crc is computed before the bytes rot, so the saver detects it
        faults.configure("ckpt.shm_stage:bit_flip:1.0:21")
        state2 = {"w": jnp.arange(16.0) * 2, "step": 2}
        assert engine.save_to_memory(2, state2, ckpt_dir)
        assert _wait(
            lambda: faults.triggered_total() > 0
            and ("ckpt.shm_stage", "bit_flip") in faults.triggered()
        )
        # corrupt shm must never reach storage
        assert _wait(lambda: not saver._persist_mutex.locked())
        faults.reset()
        assert not os.path.exists(shard_file(ckpt_dir, 2, 0))
        assert engine.latest_step(ckpt_dir) == 1
        # shm corruption is its own incident — NOT storage-degraded
        # mode (storage is healthy; shm is the bad copy)
        assert _wait(lambda: events)
        assert events[0][0] == "ckpt_shm_corrupt"
        assert not saver.degraded

        # restore: the shm proposal fails verification and downgrades
        # to the storage path -> step 1, original bytes
        step, restored = engine.load(state, ckpt_dir)
        assert step == 1
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(16.0)
        )

    def test_persistent_enospc_enters_degraded_mode(self, saver, tmp_path):
        from dlrover_tpu.obs.metrics import default_registry

        events = []
        saver.set_event_reporter(lambda ev, msg: events.append((ev, msg)))
        ckpt_dir = str(tmp_path / "ckpt")
        engine = CheckpointEngine()
        state = {"w": jnp.arange(8.0), "step": 1}

        faults.configure("ckpt.persist:enospc:1.0")
        assert engine.save_to_memory(1, state, ckpt_dir)
        assert _wait(lambda: saver.degraded), "never entered degraded mode"
        # visible in the metrics registry + as a master-bound node event
        gauge = default_registry().gauge("dlrover_ckpt_degraded_mode")
        assert gauge.value == 1.0
        assert events and events[0][0] == "ckpt_degraded"
        # nothing reached storage, commit never started
        assert engine.latest_step(ckpt_dir) == -1

        # training continues: shm-only saves still work while degraded
        faults.reset()
        state2 = {"w": jnp.arange(8.0) + 1, "step": 2}
        assert _wait(
            lambda: engine.save_to_memory(2, state2, ckpt_dir),
            timeout=30,
            interval=0.2,
        ), "save never accepted after degraded entry"
        # first healthy persist exits the mode and reports recovery
        assert _wait(lambda: not saver.degraded), "never recovered"
        assert gauge.value == 0.0
        assert ("ckpt_degraded_recovered" in {e for e, _ in events})
        assert _wait(lambda: engine.latest_step(ckpt_dir) == 2)

    def test_shard_thread_failure_fast_fails_commit(self, saver, tmp_path):
        """An exception in a per-shard persist thread must surface
        immediately — no commit thread waiting out a 600s timeout for a
        done file that will never arrive."""
        events = []
        saver.set_event_reporter(lambda ev, msg: events.append((ev, msg)))
        ckpt_dir = str(tmp_path / "ckpt")
        engine = CheckpointEngine()
        faults.configure("ckpt.shard_write:io_error:1.0")
        t0 = time.time()
        assert engine.save_to_memory(
            3, {"w": jnp.arange(4.0)}, ckpt_dir
        )
        assert _wait(lambda: len(events) > 0), "failure never surfaced"
        elapsed = time.time() - t0
        assert elapsed < 30, f"fast-fail took {elapsed:.1f}s"
        # the failure names the shard and no commit was attempted
        assert "shard 0" in events[0][1]
        assert not saver._commit_threads
        assert read_tracker(saver.storage, ckpt_dir) == -1
        faults.reset()

    def test_master_records_degraded_node_event(self):
        """run.py wires saver events to report_failure(level=warning);
        the master must surface that as a queryable node event, not a
        relaunch."""
        from dlrover_tpu.master.local_master import LocalJobMaster
        from dlrover_tpu.agent.master_client import MasterClient

        m = LocalJobMaster(port=0, node_num=1)
        m.prepare()
        try:
            c = MasterClient(m.addr, node_id=0)
            c.report_failure(
                "ckpt_degraded: step 7: shard 0: ENOSPC", level="warning"
            )
            assert _wait(
                lambda: m.job_manager.node_events("ckpt_degraded"),
                timeout=10,
            )
            ev = m.job_manager.node_events("ckpt_degraded")[0]
            assert ev["node_id"] == 0
            assert "ENOSPC" in ev["detail"]
            # a warning never marks the node broken
            node = m.job_manager.get_node("worker", 0)
            assert node is not None and not node.is_released
            c.close()
        finally:
            m.stop()


# ---------------------------------------------------------------------------
# chunked-stager crc: end-to-end shm integrity for the incremental path
# ---------------------------------------------------------------------------
class TestChunkedStagerIntegrity:
    def test_chunked_commit_publishes_record_crcs(self, saver, tmp_path):
        engine = CheckpointEngine()
        state = {"w": jnp.arange(4096.0), "b": jnp.ones(7)}
        stager = engine.begin_chunked_save(
            5, state, str(tmp_path / "ck"), chunk_bytes=1 << 10
        )
        assert stager is not None
        while stager.advance(budget_s=0.01):
            pass
        assert stager.commit()
        metas = saver._shm_handlers[0].metadata()["records"]
        assert metas and all(m.get("crc32") is not None for m in metas)
        # and the saver's verify accepts them
        step, records, _ = saver._shm_handlers[0].load_records(verify=True)
        assert step == 5

    def test_chunked_stage_corruption_detected(self, saver, tmp_path):
        engine = CheckpointEngine()
        ckpt_dir = str(tmp_path / "ck")
        faults.configure("ckpt.shm_stage:bit_flip:1.0:31")
        stager = engine.begin_chunked_save(
            6, {"w": jnp.arange(512.0)}, ckpt_dir, chunk_bytes=1 << 10
        )
        assert stager is not None
        assert stager.commit()
        faults.reset()
        with pytest.raises(ValueError, match="checksum"):
            saver._shm_handlers[0].load_records(verify=True)
        # and the saver refuses to persist the poisoned bytes
        assert _wait(lambda: not saver._persist_mutex.locked())
        assert _wait(
            lambda: not os.path.exists(shard_file(ckpt_dir, 6, 0)),
            timeout=5,
        )


# ---------------------------------------------------------------------------
# master-client retry hardening (satellite)
# ---------------------------------------------------------------------------
class TestMasterClientRetries:
    def _client(self):
        from dlrover_tpu.agent.master_client import MasterClient

        return MasterClient("localhost:1", node_id=0)

    def test_full_jitter_backoff(self, monkeypatch):
        import grpc

        c = self._client()
        bounds, sleeps = [], []
        monkeypatch.setattr(
            "dlrover_tpu.agent.master_client.random.uniform",
            lambda a, b: (bounds.append((a, b)) or 0.0),
        )
        monkeypatch.setattr(
            "dlrover_tpu.agent.master_client.time.sleep",
            lambda s: sleeps.append(s),
        )
        calls = []

        def rpc(payload, timeout=None):
            calls.append(1)
            raise grpc.RpcError("down")

        with pytest.raises(ConnectionError):
            c._call(rpc, "msg", retries=3)
        assert len(calls) == 3
        # full jitter: uniform over [0, 2**i] capped at 8
        assert bounds == [(0.0, 1.0), (0.0, 2.0)]
        c.close()

    def test_retry_budget_bounds_total_attempts(self, monkeypatch):
        import grpc

        c = self._client()
        calls = []

        def rpc(payload, timeout=None):
            calls.append(1)
            raise grpc.RpcError("down")

        with pytest.raises(ConnectionError):
            c._call(rpc, "msg", retries=5, retry_budget_s=0.0)
        assert len(calls) == 1, "exhausted budget must stop retrying"
        c.close()

    def test_non_idempotent_report_single_attempt(self):
        import grpc

        c = self._client()
        calls = []

        def rpc(payload, timeout=None):
            calls.append(1)
            raise grpc.RpcError("down")

        c._report_rpc = rpc
        with pytest.raises(ConnectionError):
            c.report("msg", retries=5, idempotent=False)
        assert len(calls) == 1
        c.close()

    def test_rpc_send_fault_point_rides_retry_path(self, monkeypatch):
        c = self._client()
        monkeypatch.setattr(
            "dlrover_tpu.agent.master_client.time.sleep", lambda s: None
        )
        served = []

        def rpc(payload, timeout=None):
            served.append(1)
            raise AssertionError("must not reach the wire")

        # every attempt's injected OSError is retried like a flaky net
        faults.configure("rpc.send:io_error:1.0")
        with pytest.raises(ConnectionError):
            c._call(rpc, "msg", retries=3)
        assert not served
        assert faults.triggered()[("rpc.send", "io_error")] == 3
        c.close()


# ---------------------------------------------------------------------------
# prefetch / reshard fault sites
# ---------------------------------------------------------------------------
class TestPipelineFaultSites:
    def test_prefetch_pull_fault_propagates_in_order(self):
        from dlrover_tpu.data.prefetch import DevicePrefetcher

        faults.configure("prefetch.pull:io_error:1.0")
        pf = DevicePrefetcher(iter([np.ones(2)]), placement=lambda x: x)
        try:
            with pytest.raises(OSError):
                for _ in pf:
                    pass
        finally:
            pf.close()
        assert ("prefetch.pull", "io_error") in faults.triggered()

    def test_reshard_gather_fault_raises(self):
        from dlrover_tpu.ckpt.reshard import reshard_state

        faults.configure("reshard.gather:io_error:1.0")
        state = {"w": np.ones(4, np.float32)}
        with pytest.raises(OSError):
            reshard_state(state, state)
