"""Ops layer numerics: Pallas flash attention (interpret mode), kernel
ring attention, AGD/WSAM, 8-bit AdamW."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.ops import (
    adamw_8bit,
    agd,
    dequantize_8bit,
    flash_attention,
    make_wsam_grad_fn,
    quantize_8bit,
)
from dlrover_tpu.ops.flash_attention import (
    flash_attention_bwd,
    flash_attention_fwd,
    flash_attention_reference,
)
from dlrover_tpu.ops.optimizers import apply_wsam_sharpness
from dlrover_tpu.ops.quantized_optim import (
    _adam8_update_jnp,
    _adam8_update_pallas,
    _to_blocks,
)


def _qkv(B=2, T=128, H=4, Hkv=4, D=32, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, T, Hkv, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, T, Hkv, D)), dtype)
    return q, k, v


class TestFlashAttention:
    # fused=True exercises the short-seq fused kernels (these shapes are
    # eligible); fused=False pins the streaming block-tiled kernels so
    # they keep coverage at non-GQA shapes too
    @pytest.mark.parametrize("fused", [True, False])
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, causal, fused):
        q, k, v = _qkv()
        ref = flash_attention_reference(q, k, v, causal=causal)
        out = flash_attention(
            q, k, v, causal=causal, force="pallas", block_q=64,
            block_k=64, allow_fused=fused,
        )
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_gqa(self):
        q, k, v = _qkv(H=8, Hkv=2)
        ref = flash_attention_reference(q, k, v)
        out = flash_attention(q, k, v, force="pallas", block_q=64)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    @pytest.mark.parametrize("fused", [True, False])
    def test_custom_mask(self, fused):
        # sliding-window mask (positions within 32 of the query)
        win = lambda qp, kp: (qp >= kp) & (qp - kp < 32)  # noqa: E731
        q, k, v = _qkv()
        ref = flash_attention_reference(q, k, v, causal=True, mask_fn=win)
        out = flash_attention(
            q, k, v, causal=True, mask_fn=win, force="pallas",
            block_q=64, allow_fused=fused,
        )
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_grads_match_reference(self):
        q, k, v = _qkv(T=128, H=4, Hkv=2)

        def lp(q, k, v):
            return (
                flash_attention(q, k, v, force="pallas", block_q=64) ** 2
            ).sum()

        def lr(q, k, v):
            return (flash_attention_reference(q, k, v) ** 2).sum()

        gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(a, b, atol=5e-4)

    @pytest.mark.parametrize("fused", [True, False])
    def test_offsets_shift_causal_mask(self, fused):
        # kernel with k_offset sees keys as "earlier" -> full visibility
        q, k, v = _qkv(T=64)
        o1, lse1 = flash_attention_fwd(
            q, k, v, causal=True, q_offset=64, k_offset=0, block_q=64,
            allow_fused=fused,
        )
        ref = flash_attention_reference(
            q, k, v, causal=True, q_offset=64, k_offset=0
        )
        np.testing.assert_allclose(o1, ref, atol=2e-5)
        # and bwd runs with the same offsets
        do = jnp.ones_like(o1)
        dq, dk, dv = flash_attention_bwd(
            q, k, v, o1, lse1, do, causal=True, q_offset=64, k_offset=0,
            allow_fused=fused,
        )
        assert dq.shape == q.shape and dk.shape == k.shape

    def test_fully_masked_rows_zero_grads(self):
        # rows whose every key is masked must get zero output AND zero
        # gradient through the pallas backward (regression: p=exp(s-lse)
        # was 1, not 0, when lse==NEG_INF)
        blind = lambda qp, kp: (qp >= kp) & (qp >= 64)  # noqa: E731
        q, k, v = _qkv(T=128)

        def lp(q, k, v):
            return (
                flash_attention(
                    q, k, v, mask_fn=blind, force="pallas", block_q=64
                )
                ** 2
            ).sum()

        def lr(q, k, v):
            return (
                flash_attention_reference(q, k, v, mask_fn=blind) ** 2
            ).sum()

        out = flash_attention(
            q, k, v, mask_fn=blind, force="pallas", block_q=64
        )
        assert float(jnp.abs(out[:, :64]).max()) == 0.0
        gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        assert float(jnp.abs(gp[0][:, :64]).max()) == 0.0
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(a, b, atol=5e-4)

    def test_odd_length_falls_back(self):
        q, k, v = _qkv(T=100)  # 100 doesn't tile into 64/128 blocks
        out = flash_attention(q, k, v)  # auto mode: should not raise
        ref = flash_attention_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5)


class TestFusedShortSeq:
    """The fused single-program kernels (T <= 1024, H == Hkv) vs the
    streaming block-tiled kernels and the jnp reference."""

    def test_dispatch_criteria(self):
        from dlrover_tpu.ops.flash_attention import _fused_eligible

        assert _fused_eligible((2, 128, 4, 32), (2, 128, 4, 32), "bthd")
        assert _fused_eligible((2, 4, 128, 32), (2, 4, 128, 32), "bhtd")
        # GQA -> streaming
        assert not _fused_eligible((2, 128, 8, 32), (2, 128, 2, 32), "bthd")
        # cross-attention shapes -> streaming
        assert not _fused_eligible((2, 64, 4, 32), (2, 128, 4, 32), "bthd")
        # long seq -> streaming
        assert not _fused_eligible(
            (2, 2048, 4, 32), (2, 2048, 4, 32), "bthd"
        )

    def test_fwd_matches_streaming(self):
        q, k, v = _qkv()
        of, lf = flash_attention_fwd(q, k, v, causal=True, block_q=64)
        os_, ls = flash_attention_fwd(
            q, k, v, causal=True, block_q=64, allow_fused=False
        )
        np.testing.assert_allclose(of, os_, atol=2e-5)
        np.testing.assert_allclose(lf, ls, atol=2e-5)

    @pytest.mark.slow  # ~27s: heaviest tier-1 test; budget-gated out
    def test_chunked_fwd_matches_full(self):
        """flash_attention_fwd_chunked (fused tiles + online merges)
        must equal the one-call forward — same o AND lse, causal and
        non-causal, so Ulysses' full-seq path can chunk onto the fused
        kernel without a numerics change."""
        from dlrover_tpu.ops.flash_attention import (
            flash_attention_fwd_chunked,
        )

        q, k, v = _qkv(T=256)
        for causal in (True, False):
            o_full, lse_full = flash_attention_fwd(
                q, k, v, causal=causal, block_q=64
            )
            o_ch, lse_ch = flash_attention_fwd_chunked(
                q, k, v, causal=causal, chunk=64
            )
            np.testing.assert_allclose(
                np.asarray(o_ch, np.float32),
                np.asarray(o_full, np.float32),
                atol=3e-5,
            )
            np.testing.assert_allclose(lse_ch, lse_full, atol=3e-5)

    def test_chunked_fwd_respects_offsets(self):
        """Global q/k offsets flow through to every tile (a ring hop
        holding a chunked long block must mask correctly)."""
        from dlrover_tpu.ops.flash_attention import (
            flash_attention_fwd_chunked,
        )

        q, k, v = _qkv(T=128)
        o_full, lse_full = flash_attention_fwd(
            q, k, v, causal=True, q_offset=128, k_offset=0, block_q=64
        )
        o_ch, lse_ch = flash_attention_fwd_chunked(
            q, k, v, causal=True, q_offset=128, k_offset=0, chunk=64
        )
        np.testing.assert_allclose(
            np.asarray(o_ch, np.float32),
            np.asarray(o_full, np.float32),
            atol=3e-5,
        )
        np.testing.assert_allclose(lse_ch, lse_full, atol=3e-5)

    def test_bwd_matches_streaming(self):
        q, k, v = _qkv()
        o, lse = flash_attention_fwd(q, k, v, causal=True, block_q=64)
        rng = np.random.default_rng(7)
        do = jnp.asarray(rng.normal(size=o.shape), o.dtype)
        gf = flash_attention_bwd(q, k, v, o, lse, do, causal=True)
        gs = flash_attention_bwd(
            q, k, v, o, lse, do, causal=True, allow_fused=False
        )
        for a, b in zip(gf, gs):
            np.testing.assert_allclose(a, b, atol=5e-4)

    def test_grads_match_reference(self):
        # H == Hkv: the custom-vjp path dispatches to the fused kernels
        q, k, v = _qkv(T=128, H=4, Hkv=4)

        def lp(q, k, v):
            return (flash_attention(q, k, v, force="pallas") ** 2).sum()

        def lr(q, k, v):
            return (flash_attention_reference(q, k, v) ** 2).sum()

        gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(a, b, atol=5e-4)

    def test_bhtd_layout_matches_bthd(self):
        q, k, v = _qkv()
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))

        def lt(qt, kt, vt):
            o = flash_attention(
                qt, kt, vt, force="pallas", layout="bhtd"
            )
            return (o**2).sum()

        def lb(q, k, v):
            return (flash_attention(q, k, v, force="pallas") ** 2).sum()

        o_t = flash_attention(qt, kt, vt, force="pallas", layout="bhtd")
        o_b = flash_attention(q, k, v, force="pallas")
        np.testing.assert_allclose(
            o_t.transpose(0, 2, 1, 3), o_b, atol=2e-5
        )
        gt = jax.grad(lt, argnums=(0, 1, 2))(qt, kt, vt)
        gb = jax.grad(lb, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gt, gb):
            np.testing.assert_allclose(
                a.transpose(0, 2, 1, 3), b, atol=5e-4
            )

    def test_custom_mask_and_masked_rows(self):
        # sliding window AND fully-blind early rows through the fused
        # backward (regression guard mirroring the streaming-path test)
        blind = lambda qp, kp: (qp >= kp) & (qp >= 64)  # noqa: E731
        q, k, v = _qkv(T=128)

        def lp(q, k, v):
            return (
                flash_attention(q, k, v, mask_fn=blind, force="pallas")
                ** 2
            ).sum()

        def lr(q, k, v):
            return (
                flash_attention_reference(q, k, v, mask_fn=blind) ** 2
            ).sum()

        out = flash_attention(q, k, v, mask_fn=blind, force="pallas")
        assert float(jnp.abs(out[:, :64]).max()) == 0.0
        gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        assert float(jnp.abs(gp[0][:, :64]).max()) == 0.0
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(a, b, atol=5e-4)

    def test_offsets(self):
        q, k, v = _qkv(T=64)
        o, lse = flash_attention_fwd(
            q, k, v, causal=True, q_offset=64, k_offset=0
        )
        ref = flash_attention_reference(
            q, k, v, causal=True, q_offset=64, k_offset=0
        )
        np.testing.assert_allclose(o, ref, atol=2e-5)

    def test_causal_skip_fully_future_kv(self):
        # a ring hop whose KV block is entirely in the future: output 0,
        # lse NEG_INF, zero grads — via the fused whole-program skip
        from dlrover_tpu.ops.flash_attention import NEG_INF

        q, k, v = _qkv(T=64)
        o, lse = flash_attention_fwd(
            q, k, v, causal=True, q_offset=0, k_offset=64
        )
        assert float(jnp.abs(o).max()) == 0.0
        assert float(lse.max()) == float(np.float32(NEG_INF))
        do = jnp.ones_like(o)
        dq, dk, dv = flash_attention_bwd(
            q, k, v, o, lse, do, causal=True, q_offset=0, k_offset=64
        )
        assert float(jnp.abs(dq).max()) == 0.0
        assert float(jnp.abs(dk).max()) == 0.0

    def test_streaming_masked_rows_via_public_entry(self):
        # allow_fused=False pins the STREAMING kernels on fused-eligible
        # shapes, keeping the original masked-row regression guard alive
        # through the public differentiable entry
        blind = lambda qp, kp: (qp >= kp) & (qp >= 64)  # noqa: E731
        q, k, v = _qkv(T=128)

        def lp(q, k, v):
            return (
                flash_attention(
                    q, k, v, mask_fn=blind, force="pallas",
                    block_q=64, allow_fused=False,
                )
                ** 2
            ).sum()

        def lr(q, k, v):
            return (
                flash_attention_reference(q, k, v, mask_fn=blind) ** 2
            ).sum()

        out = flash_attention(
            q, k, v, mask_fn=blind, force="pallas", block_q=64,
            allow_fused=False,
        )
        assert float(jnp.abs(out[:, :64]).max()) == 0.0
        gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        assert float(jnp.abs(gp[0][:, :64]).max()) == 0.0
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(a, b, atol=5e-4)

    def test_streaming_bhtd_gqa_grads(self):
        # GQA + layout="bhtd" exercises the streaming backward's bhtd
        # head-group reduction (reshape(B, Hkv, group, Tk, D).sum(2))
        q, k, v = _qkv(T=128, H=8, Hkv=2)
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))

        def lt(qt, kt, vt):
            o = flash_attention(
                qt, kt, vt, force="pallas", layout="bhtd"
            )
            return (o**2).sum()

        def lr(q, k, v):
            return (flash_attention_reference(q, k, v) ** 2).sum()

        gt = jax.grad(lt, argnums=(0, 1, 2))(qt, kt, vt)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gt, gr):
            np.testing.assert_allclose(
                a.transpose(0, 2, 1, 3), b, atol=5e-4
            )


class TestKernelRing:
    def test_ring_kernel_matches_reference(self, sp_mesh):
        from dlrover_tpu.parallel.ring_attention import ring_self_attention

        q, k, v = _qkv(T=256, H=4, Hkv=2)
        ref = flash_attention_reference(q, k, v, causal=True)
        out = ring_self_attention(
            q, k, v, sp_mesh, causal=True, use_kernel=True
        )
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_ring_kernel_grads(self, sp_mesh):
        from dlrover_tpu.parallel.ring_attention import ring_self_attention

        q, k, v = _qkv(T=256, H=4, Hkv=2)

        def lk(q, k, v):
            return (
                ring_self_attention(
                    q, k, v, sp_mesh, causal=True, use_kernel=True
                )
                ** 2
            ).sum()

        def lr(q, k, v):
            return (flash_attention_reference(q, k, v) ** 2).sum()

        gk = jax.grad(lk, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(a, b, atol=1e-3)


@pytest.fixture(scope="module")
def sp_mesh():
    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

    return build_mesh(MeshConfig(sp=4, dp=2))


class TestAGD:
    def test_converges_on_quadratic(self):
        params = {"w": jnp.full((64,), 5.0)}
        tx = agd(1e-1)
        st = tx.init(params)

        def loss(p):
            return jnp.sum(p["w"] ** 2)

        for _ in range(200):
            g = jax.grad(loss)(params)
            u, st = tx.update(g, st, params)
            params = optax.apply_updates(params, u)
        assert float(loss(params)) < 1e-3

    def test_weight_decay_and_clip(self):
        params = {"w": jnp.full((8,), 2.0)}
        tx = agd(1e-2, weight_decay=0.1, clip=1.0)
        st = tx.init(params)
        g = {"w": jnp.full((8,), 1e6)}  # huge grad: clip caps the update
        u, st = tx.update(g, st, params)
        # |update| <= lr_adjust*clip + lr*wd*|p|
        assert float(jnp.abs(u["w"]).max()) < 1.0

    def test_amsgrad_state(self):
        params = {"w": jnp.zeros((4,))}
        tx = agd(1e-3, amsgrad=True)
        st = tx.init(params)
        assert st.max_exp_avg_sq is not None
        u, st2 = tx.update({"w": jnp.ones((4,))}, st, params)
        assert float(st2.max_exp_avg_sq["w"].max()) >= 0.0


class TestWSAM:
    def _grad_fn(self, p, _batch):
        loss = jnp.sum((p["w"] - 1.0) ** 2)
        return loss, jax.grad(lambda q: jnp.sum((q["w"] - 1.0) ** 2))(p)

    def test_decoupled_converges(self):
        wg = make_wsam_grad_fn(self._grad_fn, rho=0.05, decouple=True)
        p = {"w": jnp.full((16,), 3.0)}
        tx = optax.sgd(1e-1)
        st = tx.init(p)
        for _ in range(100):
            loss, g, sh = wg(p, None)
            u, st = tx.update(g, st, p)
            u = apply_wsam_sharpness(u, sh, 1e-1)
            p = optax.apply_updates(p, u)
        assert float(loss) < 1e-2

    def test_blended_converges(self):
        wg = make_wsam_grad_fn(self._grad_fn, rho=0.05, decouple=False)
        p = {"w": jnp.full((16,), 3.0)}
        tx = optax.sgd(1e-1)
        st = tx.init(p)
        for _ in range(100):
            loss, g, sh = wg(p, None)
            assert float(jnp.abs(sh["w"]).max()) == 0.0  # zero tree
            u, st = tx.update(g, st, p)
            p = optax.apply_updates(p, u)
        assert float(loss) < 1e-2


class TestQuantizedOptim:
    def test_quant_roundtrip(self):
        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(1000,)), jnp.float32
        )
        q = quantize_8bit(x, signed=True)
        err = float(
            jnp.abs(dequantize_8bit(q) - x).max() / jnp.abs(x).max()
        )
        assert err < 0.02

    def test_tracks_fp32_adam(self):
        p8 = {
            "w": jnp.asarray(
                np.random.default_rng(1).normal(size=(8192,)), jnp.float32
            )
        }
        pf = jax.tree.map(lambda x: x, p8)
        tx8, txf = adamw_8bit(1e-2), optax.adamw(1e-2)
        s8, sf = tx8.init(p8), txf.init(pf)

        def loss(p):
            return jnp.sum((p["w"] - 1.0) ** 2)

        for _ in range(100):
            u8, s8 = tx8.update(jax.grad(loss)(p8), s8, p8)
            p8 = optax.apply_updates(p8, u8)
            uf, sf = txf.update(jax.grad(loss)(pf), sf, pf)
            pf = optax.apply_updates(pf, uf)
        # trajectories stay close despite 8-bit moments
        assert float(jnp.abs(p8["w"] - pf["w"]).max()) < 0.2
        assert float(loss(p8)) < 2.0 * float(loss(pf)) + 1.0

    def test_small_params_stay_fp32(self):
        p = {"small": jnp.zeros((16,)), "big": jnp.zeros((8192,))}
        tx = adamw_8bit(1e-3, min_quantized_size=4096)
        st = tx.init(p)
        assert isinstance(st.mu["small"], jnp.ndarray)
        assert not isinstance(st.mu["big"], jnp.ndarray)

    def test_pallas_matches_jnp_path(self):
        rng = np.random.default_rng(2)
        g = _to_blocks(
            jnp.asarray(rng.normal(size=(4096,)), jnp.float32)
        )
        mq = quantize_8bit(
            jnp.asarray(rng.normal(size=(4096,)) * 0.01, jnp.float32), True
        )
        vq = quantize_8bit(
            jnp.asarray(
                np.abs(rng.normal(size=(4096,))) * 1e-3, jnp.float32
            ),
            False,
        )
        # new scalar layout: [lrA = lr/bc1, invbc2 = 1/bc2, eps_root]
        sc = jnp.stack(
            [
                jnp.float32(1e-2 / 0.9),
                jnp.float32(1.0 / 0.99),
                jnp.float32(1e-8),
            ]
        )
        a = _adam8_update_pallas(g, mq, vq, sc, 0.9, 0.999, interpret=True)
        b = _adam8_update_jnp(g, mq, vq, sc, 0.9, 0.999)
        assert bool((a[0].codes == b[0].codes).all())
        assert bool((a[1].codes == b[1].codes).all())
        np.testing.assert_allclose(a[2], b[2], atol=1e-7)

    def test_update_is_jittable(self):
        p = {"w": jnp.zeros((8192,))}
        tx = adamw_8bit(1e-3)
        st = tx.init(p)

        @jax.jit
        def step(g, st, p):
            return tx.update(g, st, p)

        u, st2 = step({"w": jnp.ones((8192,))}, st, p)
        assert u["w"].shape == (8192,)

    def test_flat_matches_tree_form(self):
        """adamw_8bit_flat must produce the SAME trajectory as the
        per-leaf adamw_8bit (leaves padded to BLOCK boundaries inside
        the flat buffer → identical quantization blocks), across a
        mixed pytree of big (quantized) and small (fp32) leaves."""
        from dlrover_tpu.ops.quantized_optim import adamw_8bit_flat

        rng = np.random.default_rng(3)
        # 5000 is deliberately NOT a multiple of 128: exercises the
        # per-leaf padding inside the flat buffer
        p_tree = {
            "a": jnp.asarray(rng.normal(size=(5000,)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(64, 128)), jnp.float32),
            "norm": jnp.asarray(rng.normal(size=(32,)), jnp.float32),
        }
        p_flat = jax.tree.map(lambda x: x, p_tree)
        txt = adamw_8bit(1e-2, weight_decay=0.01, use_pallas=False)
        # group_elems=6000 forces the two big leaves into SEPARATE
        # groups — exercises the multi-group packing path
        txf = adamw_8bit_flat(
            1e-2, weight_decay=0.01, use_pallas=False, group_elems=6000
        )
        st, sf = txt.init(p_tree), txf.init(p_flat)

        def loss(p):
            return (
                jnp.sum((p["a"] - 1.0) ** 2)
                + jnp.sum(p["b"] ** 2)
                + jnp.sum((p["norm"] - 0.5) ** 2)
            )

        for _ in range(20):
            ut, st = txt.update(jax.grad(loss)(p_tree), st, p_tree)
            p_tree = optax.apply_updates(p_tree, ut)
            uf, sf = txf.update(jax.grad(loss)(p_flat), sf, p_flat)
            p_flat = optax.apply_updates(p_flat, uf)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            p_tree,
            p_flat,
        )

    def test_flat_groups_are_dtype_homogeneous(self):
        """A mixed f32/bf16 tree must not round f32 grads through a
        bf16 group buffer — flat and tree trajectories stay identical
        per-leaf (code-review r4 finding)."""
        from dlrover_tpu.ops.quantized_optim import adamw_8bit_flat

        rng = np.random.default_rng(7)
        p_tree = {
            "a": jnp.asarray(rng.normal(size=(8192,)), jnp.bfloat16),
            "b": jnp.asarray(rng.normal(size=(8192,)), jnp.float32),
        }
        p_flat = jax.tree.map(lambda x: x, p_tree)
        txt = adamw_8bit(1e-2, use_pallas=False)
        txf = adamw_8bit_flat(1e-2, use_pallas=False)
        st, sf = txt.init(p_tree), txf.init(p_flat)
        assert len(sf.mu) == 2  # one group per dtype

        def loss(p):
            return sum(
                jnp.sum((x.astype(jnp.float32) - 1.0) ** 2)
                for x in jax.tree.leaves(p)
            )

        for _ in range(5):
            ut, st = txt.update(jax.grad(loss)(p_tree), st, p_tree)
            p_tree = optax.apply_updates(p_tree, ut)
            uf, sf = txf.update(jax.grad(loss)(p_flat), sf, p_flat)
            p_flat = optax.apply_updates(p_flat, uf)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            p_tree,
            p_flat,
        )

    def test_eps_conventions(self):
        """eps (classic, outside sqrt) must track optax.adamw exactly on
        fp32 leaves; eps_root is the optax eps_root convention; both at
        once is an error."""
        from dlrover_tpu.ops.quantized_optim import adamw_8bit_flat

        with pytest.raises(ValueError, match="either eps"):
            adamw_8bit(eps=1e-8, eps_root=1e-8)
        with pytest.raises(ValueError, match="either eps"):
            adamw_8bit_flat(eps=1e-8, eps_root=1e-8)
        # small (fp32) leaves use the shared math: classic eps must
        # reproduce optax.adamw bit-for-bit over several steps
        p8 = {"w": jnp.asarray(np.linspace(-1, 1, 64), jnp.float32)}
        pf = jax.tree.map(lambda x: x, p8)
        tx8 = adamw_8bit(1e-2, eps=1e-8, min_quantized_size=4096)
        txf = optax.adam(1e-2, eps=1e-8)
        s8, sf = tx8.init(p8), txf.init(pf)
        for _ in range(10):
            g = {"w": jnp.cos(p8["w"])}
            u8, s8 = tx8.update(g, s8, p8)
            p8 = optax.apply_updates(p8, u8)
            uf, sf = txf.update(g, sf, pf)
            pf = optax.apply_updates(pf, uf)
        np.testing.assert_allclose(
            np.asarray(p8["w"]), np.asarray(pf["w"]), rtol=1e-6
        )

    def test_flat_rejected_on_sharded_strategy(self):
        """The trainer refuses adamw_8bit_flat with model-sharded
        meshes (it would silently defeat ZeRO/TP sharding)."""
        from dlrover_tpu.accel.strategy import Strategy
        from dlrover_tpu.ops.quantized_optim import adamw_8bit_flat
        from dlrover_tpu.parallel.mesh import MeshConfig
        from dlrover_tpu.trainer.elastic.trainer import (
            ElasticTrainer,
            TrainerConfig,
        )

        class _Toks:
            def __len__(self):
                return 16

            def __getitem__(self, i):
                z = np.zeros(33, np.int32)
                return {"x": z[:-1], "y": z[1:]}

        from dlrover_tpu.models import tiny

        with pytest.raises(ValueError, match="adamw_8bit_flat"):
            ElasticTrainer(
                model_cfg=tiny(),
                tx=adamw_8bit_flat(1e-3),
                dataset=_Toks(),
                trainer_cfg=TrainerConfig(
                    batch_size=8, seq_len=32, report_metrics=False
                ),
                strategy=Strategy(
                    mesh=MeshConfig(fsdp=8), dtype="float32"
                ),
            )

    def test_flat_pallas_kernel_matches_jnp(self):
        """The aliased one-pass flat kernel (interpret mode) must agree
        with the jnp math bit-for-bit on codes."""
        from dlrover_tpu.ops.quantized_optim import (
            _FLAT_ROWS,
            Quantized8,
            _adam8_update_jnp,
            _adam8_update_pallas_flat,
            _quant_block_math_wide,
        )

        rng = np.random.default_rng(5)
        n = 2 * _FLAT_ROWS * 128  # exactly 2 grid chunks, as the packer emits
        g = _to_blocks(jnp.asarray(rng.normal(size=(n,)), jnp.float32))

        def wideq(x, signed):
            c, s = _quant_block_math_wide(_to_blocks(x), signed)
            return Quantized8(c, s, (n,), signed)

        mq = wideq(
            jnp.asarray(rng.normal(size=(n,)) * 0.01, jnp.float32), True
        )
        vq = wideq(
            jnp.asarray(np.abs(rng.normal(size=(n,))) * 1e-3, jnp.float32),
            False,
        )
        # new scalar layout: [lrA = lr/bc1, invbc2 = 1/bc2, eps_root]
        sc = jnp.stack(
            [
                jnp.float32(1e-2 / 0.9),
                jnp.float32(1.0 / 0.99),
                jnp.float32(1e-8),
            ]
        )
        a = _adam8_update_pallas_flat(
            g, mq, vq, sc, 0.9, 0.999, interpret=True
        )
        b = _adam8_update_jnp(g, mq, vq, sc, 0.9, 0.999)
        # codes may differ by +-1 on exact rounding-boundary ties
        # (compiler fp ordering); anything more is a real math bug
        for x, y in ((a[0], b[0]), (a[1], b[1])):
            d = np.abs(
                np.asarray(x.codes, np.int32) - np.asarray(y.codes, np.int32)
            )
            assert d.max() <= 1 and (d > 0).mean() < 1e-4, (
                d.max(), (d > 0).mean(),
            )
        np.testing.assert_allclose(a[2], b[2], atol=1e-6)

    def test_flat_is_jittable_and_compact(self):
        """The flat state is ONE quantized buffer pair + one small f32
        pair regardless of leaf count, and updates under jit."""
        from dlrover_tpu.ops.quantized_optim import (
            Adam8FlatState,
            adamw_8bit_flat,
        )

        p = {f"w{i}": jnp.zeros((8192,)) for i in range(6)}
        p["tiny"] = jnp.zeros((8,))
        tx = adamw_8bit_flat(1e-3, use_pallas=False)
        st = tx.init(p)
        assert isinstance(st, Adam8FlatState)
        # all six big leaves land in ONE group (<< group_elems),
        # padded up to one BLOCK*_FLAT_ROWS grid chunk
        assert len(st.mu) == 1
        assert st.mu[0].codes.shape[0] * 128 == 2048 * 128
        assert st.mu_small.shape == (8,)

        @jax.jit
        def step(g, st, p):
            return tx.update(g, st, p)

        g = jax.tree.map(jnp.ones_like, p)
        u, st2 = step(g, st, p)
        assert u["w0"].shape == (8192,)
        assert u["tiny"].shape == (8,)
        assert int(st2.count) == 1

    def test_4bit_roundtrip_and_memory(self):
        from dlrover_tpu.ops.quantized_optim import (
            dequantize_4bit,
            quantize_4bit,
        )

        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(4096,)), jnp.float32
        )
        q = quantize_4bit(x, signed=True)
        assert q.packed.dtype == jnp.uint8
        assert q.packed.size == 2048  # two codes per byte: 8x under fp32
        err = float(
            jnp.abs(dequantize_4bit(q) - x).max() / jnp.abs(x).max()
        )
        assert err < 0.2  # 4-bit sqrt map: coarse but bounded

    def test_4bit_adam_tracks_fp32(self):
        from dlrover_tpu.ops.quantized_optim import adamw_4bit

        p4 = {
            "w": jnp.asarray(
                np.random.default_rng(1).normal(size=(8192,)), jnp.float32
            )
        }
        pf = jax.tree.map(lambda x: x, p4)
        tx4, txf = adamw_4bit(learning_rate=1e-2), optax.adamw(1e-2)
        s4, sf = tx4.init(p4), txf.init(pf)

        def loss(p):
            return jnp.sum((p["w"] - 1.0) ** 2)

        @jax.jit
        def step4(g, s, p):
            return tx4.update(g, s, p)

        for _ in range(100):
            u4, s4 = step4(jax.grad(loss)(p4), s4, p4)
            p4 = optax.apply_updates(p4, u4)
            uf, sf = txf.update(jax.grad(loss)(pf), sf, pf)
            pf = optax.apply_updates(pf, uf)
        # 4-bit first moment is coarse per-coordinate, but the OBJECTIVE
        # must track fp32 Adam closely (the meaningful criterion for a
        # quantized optimizer; individual coordinates wander within the
        # quantization noise floor)
        assert float(loss(p4)) < 1.5 * float(loss(pf)) + 10.0


class TestModelUsesFlash:
    def test_transformer_attention_dispatches(self):
        # _causal_attention now routes through ops.flash_attention
        from dlrover_tpu.models.transformer import _causal_attention

        q, k, v = _qkv(T=64)
        out = _causal_attention(q, k, v)
        ref = flash_attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5)


# ---------------------------------------------------------------------------
# int8 quantized matmul (AQT-style, the FP8-optimization analog)
# ---------------------------------------------------------------------------
def test_int8_matmul_accuracy():
    from dlrover_tpu.ops import int8_matmul

    rng = np.random.default_rng(0)
    a = rng.normal(size=(64, 128)).astype(np.float32)
    b = rng.normal(size=(128, 32)).astype(np.float32)
    exact = a @ b
    got = np.asarray(int8_matmul(jnp.asarray(a), jnp.asarray(b)))
    # per-slice symmetric int8: relative error ~1/127 per operand
    rel = np.abs(got - exact) / (np.abs(exact) + 1e-3)
    assert float(np.median(rel)) < 0.05, float(np.median(rel))


def test_int8_matmul_ste_grads():
    """Straight-through backward equals the exact matmul's gradients."""
    from dlrover_tpu.ops import int8_matmul

    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))

    da, db = jax.grad(lambda a, b: jnp.sum(int8_matmul(a, b) ** 2), (0, 1))(
        a, b
    )
    # cotangent g = 2*out; STE: da = g @ b.T, db = a.T @ g with the
    # QUANTIZED out inside g
    out = int8_matmul(a, b)
    np.testing.assert_allclose(
        np.asarray(da), np.asarray(2 * out @ b.T), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(db), np.asarray(a.T @ (2 * out)), rtol=1e-5, atol=1e-5
    )


def test_int8_mlp_trains():
    """tiny model with int8 MLP projections still converges."""
    import optax

    from dlrover_tpu.models import init_params, tiny
    from dlrover_tpu.models.transformer import loss_fn

    cfg = tiny(int8_mlp=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tx = optax.adamw(1e-2)
    opt = tx.init(params)
    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)

    @jax.jit
    def step(params, opt):
        l, g = jax.value_and_grad(lambda p: loss_fn(p, x, x, cfg))(params)
        upd, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, upd), opt, l

    losses = []
    for _ in range(8):
        params, opt, l = step(params, opt)
        losses.append(float(l))
    assert losses[-1] < losses[0] - 0.5, losses
