"""Tiered embedding storage: eviction, fault-in, training continuity."""

import numpy as np
import pytest

from dlrover_tpu.ops.embedding import ShardedKvEmbedding
from dlrover_tpu.ops.embedding.tiered import TieredKvEmbedding

DIM = 8


@pytest.fixture()
def tiered(tmp_path):
    t = TieredKvEmbedding(
        ShardedKvEmbedding(2, DIM, seed=0),
        str(tmp_path / "cold.db"),
    )
    yield t
    t.close()


class TestTieredEmbedding:
    def test_evict_and_fault_in_roundtrip(self, tiered):
        keys = np.arange(100, dtype=np.int64)
        before = tiered.gather(keys).copy()
        tiered.sparse_adagrad(keys, np.ones((100, DIM), np.float32), lr=0.1)
        trained = tiered.gather(keys, insert_missing=False).copy()

        evicted = tiered.evict_cold(ts_limit=2**62)  # everything is cold
        assert evicted == 100
        assert tiered.hot_rows() == 0 and tiered.cold_rows() == 100

        # fault-in on gather: exact values come back, slots included
        back = tiered.gather(keys, insert_missing=False)
        np.testing.assert_array_equal(back, trained)
        assert tiered.hot_rows() == 100 and tiered.cold_rows() == 0
        # optimizer slots survived the round trip: next update identical
        ref = ShardedKvEmbedding(2, DIM, seed=0)
        ref.gather(keys)
        ref.sparse_adagrad(keys, np.ones((100, DIM), np.float32), lr=0.1)
        ref.sparse_adagrad(
            keys, np.full((100, DIM), 0.5, np.float32), lr=0.1
        )
        tiered.sparse_adagrad(
            keys, np.full((100, DIM), 0.5, np.float32), lr=0.1
        )
        np.testing.assert_array_equal(
            tiered.gather(keys, insert_missing=False),
            ref.gather(keys, insert_missing=False),
        )

    def test_partial_eviction_keeps_hot_rows(self, tiered):
        cold_keys = np.arange(50, dtype=np.int64)
        tiered.gather(cold_keys)
        for s in tiered.hot.shards:  # backdate: make them look old
            k, rows, f, ts = s.export()
            s.import_rows(k, rows, f, np.ones_like(ts))
        hot_keys = np.arange(100, 120, dtype=np.int64)
        tiered.gather(hot_keys)

        evicted = tiered.evict_cold(ts_limit=100)
        assert evicted == 50
        assert tiered.hot_rows() == 20
        # mixed gather: 30 faulted + 20 hot + 5 fresh
        mixed = np.concatenate([cold_keys[:30], hot_keys, [500, 501, 502, 503, 504]])
        out = tiered.gather(mixed)
        assert out.shape == (55, DIM)
        assert tiered.cold_rows() == 20  # the 20 un-gathered cold rows

    def test_export_state_includes_cold_tier(self, tiered, tmp_path):
        """Checkpoints of a tiered store must carry evicted rows — the
        cold.db file is not part of the checkpoint."""
        keys = np.arange(60, dtype=np.int64)
        tiered.gather(keys)
        trained = tiered.gather(keys, insert_missing=False).copy()
        tiered.evict_cold(ts_limit=2**62)
        assert tiered.hot_rows() == 0

        state = tiered.export_state()
        assert len(state["keys"]) == 60  # all rows, despite empty hot tier
        fresh = ShardedKvEmbedding(2, DIM, seed=7)
        fresh.import_state(state)
        np.testing.assert_array_equal(
            fresh.gather(keys, insert_missing=False), trained
        )

    def test_incremental_ckpt_over_tiered_store(self, tiered, tmp_path):
        from dlrover_tpu.ops.embedding import IncrementalCheckpointManager

        keys = np.arange(40, dtype=np.int64)
        tiered.gather(keys)
        mgr = IncrementalCheckpointManager(
            tiered, str(tmp_path / "ckpt"), full_every=10
        )
        mgr.save(step=1)  # full
        tiered.evict_cold(ts_limit=2**62)  # everything goes cold
        mgr.save(step=2)  # delta must carry the newly evicted rows
        live = tiered.gather(keys, insert_missing=False).copy()

        fresh = TieredKvEmbedding(
            ShardedKvEmbedding(2, DIM, seed=9),
            str(tmp_path / "cold2.db"),
        )
        mgr2 = IncrementalCheckpointManager(fresh, str(tmp_path / "ckpt"))
        assert mgr2.restore() == 2
        np.testing.assert_array_equal(
            fresh.gather(keys, insert_missing=False), live
        )
        fresh.close()

    def test_unknown_keys_follow_base_rules(self, tiered):
        out = tiered.gather([9999], insert_missing=False)
        np.testing.assert_array_equal(out, np.zeros((1, DIM), np.float32))
        assert tiered.hot_rows() == 0
