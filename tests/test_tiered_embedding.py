"""Tiered embedding storage: eviction, fault-in, training continuity."""

import threading
import time

import numpy as np
import pytest

from dlrover_tpu.ops.embedding import ShardedKvEmbedding
from dlrover_tpu.ops.embedding.tiered import (
    NativeTieredKvEmbedding,
    TieredKvEmbedding,
)

DIM = 8


def _make_tiered(kind, path, num_shards=2, seed=0):
    hot = ShardedKvEmbedding(num_shards, DIM, seed=seed)
    if kind == "native":
        return NativeTieredKvEmbedding(hot, str(path))
    return TieredKvEmbedding(hot, str(path))


# every semantic test runs against BOTH tier managers: the Python/sqlite
# one and the native (C++ spill-log) one — one contract, two engines
@pytest.fixture(params=["sqlite", "native"])
def tiered(tmp_path, request):
    t = _make_tiered(request.param, tmp_path / f"cold.{request.param}")
    t._kind = request.param
    yield t
    t.close()


class TestTieredEmbedding:
    def test_evict_and_fault_in_roundtrip(self, tiered):
        keys = np.arange(100, dtype=np.int64)
        before = tiered.gather(keys).copy()
        tiered.sparse_adagrad(keys, np.ones((100, DIM), np.float32), lr=0.1)
        trained = tiered.gather(keys, insert_missing=False).copy()

        evicted = tiered.evict_cold(ts_limit=2**62)  # everything is cold
        assert evicted == 100
        assert tiered.hot_rows() == 0 and tiered.cold_rows() == 100

        # fault-in on gather: exact values come back, slots included
        back = tiered.gather(keys, insert_missing=False)
        np.testing.assert_array_equal(back, trained)
        assert tiered.hot_rows() == 100 and tiered.cold_rows() == 0
        # optimizer slots survived the round trip: next update identical
        ref = ShardedKvEmbedding(2, DIM, seed=0)
        ref.gather(keys)
        ref.sparse_adagrad(keys, np.ones((100, DIM), np.float32), lr=0.1)
        ref.sparse_adagrad(
            keys, np.full((100, DIM), 0.5, np.float32), lr=0.1
        )
        tiered.sparse_adagrad(
            keys, np.full((100, DIM), 0.5, np.float32), lr=0.1
        )
        np.testing.assert_array_equal(
            tiered.gather(keys, insert_missing=False),
            ref.gather(keys, insert_missing=False),
        )

    def test_partial_eviction_keeps_hot_rows(self, tiered):
        cold_keys = np.arange(50, dtype=np.int64)
        tiered.gather(cold_keys)
        for s in tiered.hot.shards:  # backdate: make them look old
            k, rows, f, ts = s.export()
            s.import_rows(k, rows, f, np.ones_like(ts))
        hot_keys = np.arange(100, 120, dtype=np.int64)
        tiered.gather(hot_keys)

        evicted = tiered.evict_cold(ts_limit=100)
        assert evicted == 50
        assert tiered.hot_rows() == 20
        # mixed gather: 30 faulted + 20 hot + 5 fresh
        mixed = np.concatenate([cold_keys[:30], hot_keys, [500, 501, 502, 503, 504]])
        out = tiered.gather(mixed)
        assert out.shape == (55, DIM)
        assert tiered.cold_rows() == 20  # the 20 un-gathered cold rows

    def test_export_state_includes_cold_tier(self, tiered, tmp_path):
        """Checkpoints of a tiered store must carry evicted rows — the
        cold.db file is not part of the checkpoint."""
        keys = np.arange(60, dtype=np.int64)
        tiered.gather(keys)
        trained = tiered.gather(keys, insert_missing=False).copy()
        tiered.evict_cold(ts_limit=2**62)
        assert tiered.hot_rows() == 0

        state = tiered.export_state()
        assert len(state["keys"]) == 60  # all rows, despite empty hot tier
        fresh = ShardedKvEmbedding(2, DIM, seed=7)
        fresh.import_state(state)
        np.testing.assert_array_equal(
            fresh.gather(keys, insert_missing=False), trained
        )

    def test_incremental_ckpt_over_tiered_store(self, tiered, tmp_path):
        from dlrover_tpu.ops.embedding import IncrementalCheckpointManager

        keys = np.arange(40, dtype=np.int64)
        tiered.gather(keys)
        mgr = IncrementalCheckpointManager(
            tiered, str(tmp_path / "ckpt"), full_every=10
        )
        mgr.save(step=1)  # full
        tiered.evict_cold(ts_limit=2**62)  # everything goes cold
        mgr.save(step=2)  # delta must carry the newly evicted rows
        live = tiered.gather(keys, insert_missing=False).copy()

        fresh = _make_tiered(
            tiered._kind, tmp_path / "cold2", seed=9
        )
        mgr2 = IncrementalCheckpointManager(fresh, str(tmp_path / "ckpt"))
        assert mgr2.restore() == 2
        np.testing.assert_array_equal(
            fresh.gather(keys, insert_missing=False), live
        )
        fresh.close()

    def test_unknown_keys_follow_base_rules(self, tiered):
        out = tiered.gather([9999], insert_missing=False)
        np.testing.assert_array_equal(out, np.zeros((1, DIM), np.float32))
        assert tiered.hot_rows() == 0


class TestNativeColdTier:
    """Native-only semantics: spill-log persistence across reopen and
    the throughput reason the native tier exists."""

    def test_spill_log_survives_restart(self, tmp_path):
        t = _make_tiered("native", tmp_path / "cold")
        keys = np.arange(80, dtype=np.int64)
        t.gather(keys)
        t.sparse_adagrad(keys, np.ones((80, DIM), np.float32), lr=0.1)
        trained = t.gather(keys, insert_missing=False).copy()
        assert t.evict_cold(ts_limit=2**62) == 80
        t.close()

        # a NEW process/table over the same spill logs: index rebuilds
        # by scan, rows (incl. slots) fault back exactly
        t2 = _make_tiered("native", tmp_path / "cold")
        assert t2.hot_rows() == 0 and t2.cold_rows() == 80
        np.testing.assert_array_equal(
            t2.gather(keys, insert_missing=False), trained
        )
        assert t2.cold_rows() == 0
        t2.close()

    def test_tombstones_survive_restart(self, tmp_path):
        t = _make_tiered("native", tmp_path / "cold")
        keys = np.arange(20, dtype=np.int64)
        t.gather(keys)
        t.evict_cold(ts_limit=2**62)
        t.gather(keys[:10], insert_missing=False)  # fault half back
        assert t.cold_rows() == 10
        t.close()
        t2 = _make_tiered("native", tmp_path / "cold")
        # the faulted-in half must NOT resurrect from stale log records
        assert t2.cold_rows() == 10
        out = t2.gather(keys[10:], insert_missing=False)
        assert out.shape == (10, DIM)
        t2.close()

    def test_delta_export_seq_survives_restart(self, tmp_path):
        t = _make_tiered("native", tmp_path / "cold")
        keys = np.arange(30, dtype=np.int64)
        t.gather(keys)
        t.evict_cold(ts_limit=2**62)
        seq = t._evict_seq
        t.close()
        t2 = _make_tiered("native", tmp_path / "cold")
        # eviction sequencing continues past the restart (a delta
        # consumer's cursor stays meaningful)
        assert t2._evict_seq == seq
        t2.gather(keys)
        t2.evict_cold(ts_limit=2**62)
        assert t2._evict_seq == seq + 1
        t2.close()

    @pytest.mark.slow
    def test_native_faulting_gather_beats_sqlite(self, tmp_path):
        """The reason the tier manager is native: gather-with-fault
        throughput. Evict a zipfian table, then time faulting gathers.

        Marked slow (out of tier-1): it compares two wall-clock timings
        on a shared CI box, and env-speed jitter (noisy neighbors, cold
        page cache on the sqlite leg's first run) flips the 1.5x bar a
        few percent of runs even with best-of-N — a comparative perf
        assertion needs a quiet machine, which the slow tier gets.
        Best-of-3 per backend keeps the signal honest when it does run."""
        import time

        n, batch = 20000, 512
        rng = np.random.default_rng(0)
        times = {}
        for kind in ("sqlite", "native"):
            t = _make_tiered(kind, tmp_path / f"perf.{kind}")
            keys = np.arange(n, dtype=np.int64)
            best = float("inf")
            for rep in range(3):
                t.gather(keys)
                t.evict_cold(ts_limit=2**62)
                t0 = time.perf_counter()
                for i in range(0, n, batch):
                    t.gather(keys[i : i + batch], insert_missing=False)
                best = min(best, time.perf_counter() - t0)
                assert t.cold_rows() == 0
            times[kind] = best
            t.close()
        assert times["native"] <= times["sqlite"] * 1.5, times

    def test_reshard_preserves_cold_rows(self, tmp_path):
        """Key->shard routing changes with the shard count, so reshard
        faults every cold row hot first and restarts the spill logs —
        no evicted row may be lost or shadowed."""
        t = _make_tiered("native", tmp_path / "cold")
        keys = np.arange(200, dtype=np.int64)
        t.gather(keys)
        t.sparse_adagrad(keys, np.ones((200, DIM), np.float32), lr=0.1)
        trained = t.gather(keys, insert_missing=False).copy()
        t.evict_cold(ts_limit=2**62)
        assert t.cold_rows() == 200
        t.reshard(4)
        assert t.hot.num_shards == 4
        assert t.cold_rows() == 0 and t.hot_rows() == 200
        np.testing.assert_array_equal(
            t.gather(keys, insert_missing=False), trained
        )
        # the tier keeps working after the reshard
        t.evict_cold(ts_limit=2**62)
        assert t.cold_rows() == 200
        np.testing.assert_array_equal(
            t.gather(keys, insert_missing=False), trained
        )
        t.close()

    def test_reopen_with_fewer_shards_refused(self, tmp_path):
        t = _make_tiered("native", tmp_path / "cold", num_shards=4)
        keys = np.arange(100, dtype=np.int64)
        t.gather(keys)
        t.evict_cold(ts_limit=2**62)
        t.close()
        with pytest.raises(ValueError, match="live rows"):
            _make_tiered("native", tmp_path / "cold", num_shards=2)

    def test_torn_tail_record_is_dropped_on_open(self, tmp_path):
        """A writer crash mid-append leaves a torn tail record; reopen
        must recover everything before it and drop only the tail."""
        import os

        t = _make_tiered("native", tmp_path / "cold", num_shards=1)
        keys = np.arange(50, dtype=np.int64)
        t.gather(keys)
        trained = t.gather(keys, insert_missing=False).copy()
        t.evict_cold(ts_limit=2**62)
        t.close()
        log = f"{tmp_path / 'cold'}.shard0"
        size = os.path.getsize(log)
        with open(log, "r+b") as f:  # tear the last record's payload
            f.truncate(size - 17)
        t2 = _make_tiered("native", tmp_path / "cold", num_shards=1)
        assert t2.cold_rows() == 49  # the torn record dropped, rest live
        back = t2.gather(keys, insert_missing=False)
        survivors = [k for k in range(50) if not np.all(back[k] == 0)]
        assert len(survivors) == 49
        for k in survivors:
            np.testing.assert_array_equal(back[k], trained[k])
        t2.close()


class TestExportUnderConcurrentFaultIn:
    def test_export_never_drops_rows_during_gathers(self, tiered):
        """ADVICE r5: a concurrent fault-in (gather) between the hot
        and cold export legs must not drop a trained row from the
        checkpoint. Export now snapshots cold-then-hot under the tier
        read lock; gathers hammer the same keys throughout."""
        import threading

        keys = np.arange(200, dtype=np.int64)
        tiered.gather(keys)
        tiered.sparse_adagrad(
            keys, np.ones((200, DIM), np.float32), lr=0.1
        )
        assert tiered.evict_cold(ts_limit=2**62) == 200

        stop = threading.Event()
        errors = []

        def hammer():
            rng = np.random.default_rng(1)
            try:
                while not stop.is_set():
                    sub = rng.choice(keys, size=32, replace=False)
                    tiered.gather(
                        np.asarray(sub, np.int64),
                        insert_missing=False,
                    )
            except Exception as e:  # surfaced below
                errors.append(e)

        th = threading.Thread(target=hammer, daemon=True)
        th.start()
        try:
            for _ in range(10):
                state = tiered.export_state()
                got = set(int(k) for k in state["keys"])
                missing = set(int(k) for k in keys) - got
                assert not missing, (
                    f"export dropped {len(missing)} rows mid-fault-in"
                )
        finally:
            stop.set()
            th.join(timeout=10)
        assert not errors, errors


class TestRWLockContention:
    """The tier lock's docstring promises writer preference and
    TOCTOU-free tier moves; these gate it under real thread contention
    (satellite of ISSUE 12 — nothing exercised the lock concurrently)."""

    def test_writer_not_starved_by_gather_storm(self):
        """Readers arrive continuously and overlap each other; a
        writer-preferring lock admits the writer anyway (a plain
        readers-first lock wedges here until the storm stops)."""
        from dlrover_tpu.ops.embedding.tiered import _RWLock

        lock = _RWLock()
        stop = threading.Event()
        acquired = threading.Event()

        def reader():
            while not stop.is_set():
                lock.acquire_read()
                time.sleep(0.001)
                lock.release_read()

        readers = [
            threading.Thread(target=reader, daemon=True)
            for _ in range(6)
        ]
        for r in readers:
            r.start()
        time.sleep(0.05)  # the storm is rolling

        def writer():
            lock.acquire_write()
            acquired.set()
            lock.release_write()

        w = threading.Thread(target=writer, daemon=True)
        w.start()
        ok = acquired.wait(timeout=5.0)
        stop.set()
        w.join(timeout=2.0)
        for r in readers:
            r.join(timeout=2.0)
        assert ok, "writer starved by overlapping readers"

    def test_new_readers_wait_behind_queued_writer(self):
        from dlrover_tpu.ops.embedding.tiered import _RWLock

        lock = _RWLock()
        lock.acquire_read()
        order = []

        def writer():
            lock.acquire_write()
            order.append("w")
            lock.release_write()

        def late_reader():
            lock.acquire_read()
            order.append("r")
            lock.release_read()

        w = threading.Thread(target=writer, daemon=True)
        w.start()
        time.sleep(0.05)  # writer is queued on the held read lock
        r = threading.Thread(target=late_reader, daemon=True)
        r.start()
        time.sleep(0.05)
        assert order == []  # the late reader must NOT slip past
        lock.release_read()
        w.join(timeout=2.0)
        r.join(timeout=2.0)
        assert order[0] == "w"

    def test_gather_storm_vs_eviction_no_row_resurrection(self, tiered):
        """The documented TOCTOU: a gather probing the hot tier just
        before eviction moves a row out must not re-initialize it just
        after (shadowing the cold copy with a fresh row). Under a
        concurrent gather storm + eviction loop every row must keep its
        trained value."""
        keys = np.arange(200, dtype=np.int64)
        tiered.gather(keys)
        tiered.sparse_adagrad(
            keys, np.ones((200, DIM), np.float32), lr=0.1
        )
        trained = tiered.gather(keys, insert_missing=False).copy()
        stop = threading.Event()
        errs = []

        def storm(seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                sub = rng.choice(keys, 32, replace=False)
                got = tiered.gather(sub)
                try:
                    np.testing.assert_array_equal(
                        got, trained[sub]
                    )
                except AssertionError as e:  # resurrection = data loss
                    errs.append(e)
                    return

        threads = [
            threading.Thread(target=storm, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 1.5
        evictions = 0
        while time.monotonic() < deadline and not errs:
            evictions += 1
            tiered.evict_cold(ts_limit=2**62)  # everything is "old"
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        assert not errs, errs[0]
        assert evictions > 3
        np.testing.assert_array_equal(
            tiered.gather(keys, insert_missing=False), trained
        )


class TestTieredWarmReshard:
    def test_warm_reshard_preserves_both_tiers(self, tiered):
        keys = np.arange(120, dtype=np.int64)
        tiered.gather(keys)
        tiered.sparse_adagrad(
            keys, np.ones((120, DIM), np.float32), lr=0.2
        )
        trained = tiered.gather(keys, insert_missing=False).copy()
        # half the rows go disk-cold before the reshard
        tiered.evict_cold(ts_limit=2**62)
        assert tiered.cold_rows() > 0
        report = tiered.warm_reshard(3)
        assert tiered.hot.num_shards == 3
        if tiered._kind == "native":
            # per-shard spill logs fault back hot first, so the report
            # covers every row; the sqlite tier is key-addressed and
            # its cold rows never move (report covers hot rows only)
            assert report.total_rows == 120
        np.testing.assert_array_equal(
            tiered.gather(keys, insert_missing=False), trained
        )
        # checkpoints still see every row
        assert len(tiered.export_state()["keys"]) == 120
