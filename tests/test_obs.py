"""Unified telemetry (dlrover_tpu/obs/): span tracer, metrics registry,
master-side straggler/hang aggregation, the monitor satellites, and the
trace artifact of a real smoke training run.

Acceptance anchors (ISSUE 4):
- a smoke training run dumps Chrome-trace JSON whose step spans are
  ≥95% covered by phase children, loaded + validated here;
- with one worker's step times inflated 3x the master flags exactly
  that worker and the signal reaches Brain ingestion;
- hang reports carry last-open-span attribution;
- every PipelineStats dataclass field must appear in as_dict() and the
  registry export (the drift tripwire).
"""

import dataclasses
import json
import os
import threading
import time

import numpy as np
import pytest

from dlrover_tpu.obs import trace as obs_trace
from dlrover_tpu.obs.aggregate import TelemetryAggregator
from dlrover_tpu.obs.metrics import (
    PIPELINE_PREFIX,
    MetricsRegistry,
    fold_pipeline_stats,
)
from dlrover_tpu.obs.trace import (
    SpanHeartbeat,
    SpanTracer,
    step_coverage,
    validate_chrome_trace,
)


class TestSpanTracer:
    def test_records_span_with_duration(self):
        t = SpanTracer(enabled=True)
        with t.span("work"):
            time.sleep(0.005)
        assert len(t) == 1
        name, tid, start_ns, dur_ns, depth, attrs, _seq = list(t._buf)[0]
        assert name == "work"
        assert tid == threading.get_ident()
        assert dur_ns >= 4_000_000  # slept 5ms
        assert depth == 0

    def test_nesting_depth_recorded(self):
        t = SpanTracer(enabled=True)
        with t.span("outer"):
            with t.span("inner"):
                pass
        by_name = {r[0]: r for r in t._buf}
        assert by_name["outer"][4] == 0
        assert by_name["inner"][4] == 1

    def test_ring_buffer_bounds_memory(self):
        t = SpanTracer(capacity=16, enabled=True)
        for _ in range(100):
            with t.span("s"):
                pass
        assert len(t) == 16
        assert t.dropped == 84

    def test_disabled_is_noop(self):
        t = SpanTracer(enabled=False)
        sp = t.span("x")
        assert sp is t.span("y")  # shared singleton, no allocation
        with sp:
            pass
        assert len(t) == 0

    def test_cancel_discards(self):
        t = SpanTracer(enabled=True)
        sp = t.span("aborted")
        sp.cancel()
        assert len(t) == 0
        assert t.open_spans() == []

    def test_double_end_is_idempotent(self):
        t = SpanTracer(enabled=True)
        sp = t.span("once")
        sp.end()
        sp.end()
        assert len(t) == 1

    def test_attrs_and_set(self):
        t = SpanTracer(enabled=True)
        with t.span("resize_compile", mesh="dp4") as sp:
            sp.set(cache_hit=True)
        rec = list(t._buf)[0]
        assert rec[5] == {"mesh": "dp4", "cache_hit": True}

    def test_decorator(self):
        t = SpanTracer(enabled=True)

        @t.traced("named")
        def f(x):
            return x + 1

        assert f(1) == 2
        assert list(t._buf)[0][0] == "named"

    def test_chrome_export_valid_and_dump_roundtrips(self, tmp_path):
        t = SpanTracer(enabled=True)
        with t.span("step"):
            with t.span("compute"):
                pass
        path = str(tmp_path / "sub" / "trace.json")
        t.dump(path)
        loaded = json.load(open(path))
        ok, reason = validate_chrome_trace(loaded)
        assert ok, reason
        xs = [e for e in loaded["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"step", "compute"}
        # depth rides in args so coverage is recomputable offline
        assert all("depth" in e["args"] for e in xs)

    def test_validate_rejects_garbage(self):
        assert validate_chrome_trace({"nope": 1})[0] is False
        assert validate_chrome_trace({"traceEvents": []})[0] is False
        assert validate_chrome_trace(
            {"traceEvents": [{"ph": "X", "name": "a"}]}
        )[0] is False

    def test_open_spans_visible_cross_thread(self):
        t = SpanTracer(enabled=True)
        entered = threading.Event()
        release = threading.Event()

        def worker():
            with t.span("ckpt_commit"):
                entered.set()
                release.wait(5.0)

        th = threading.Thread(target=worker, daemon=True)
        th.start()
        assert entered.wait(5.0)
        time.sleep(0.02)
        last = t.last_open_span()
        assert last is not None
        assert last[0] == "ckpt_commit"
        assert last[1] > 0
        release.set()
        th.join(5.0)
        assert t.last_open_span() is None

    def test_last_open_span_tid_filter(self):
        t = SpanTracer(enabled=True)
        entered = threading.Event()
        release = threading.Event()

        def parked_producer():
            with t.span("prefetch_pull"):
                entered.set()
                release.wait(5.0)

        th = threading.Thread(target=parked_producer, daemon=True)
        th.start()
        assert entered.wait(5.0)
        sp = t.span("compute")
        try:
            my_tid = threading.get_ident()
            # unfiltered may pick the producer; filtered must not
            assert t.last_open_span(tid=my_tid)[0] == "compute"
        finally:
            sp.end()
            release.set()
            th.join(5.0)

    def test_threaded_recording_is_safe(self):
        t = SpanTracer(capacity=10_000, enabled=True)

        def burst():
            for _ in range(200):
                with t.span("s"):
                    pass

        threads = [
            threading.Thread(target=burst) for _ in range(4)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len(t) == 800

    def test_reset_clears_records(self):
        t = SpanTracer(enabled=True)
        with t.span("a"):
            pass
        t.reset()
        assert len(t) == 0
        assert t.dropped == 0


class TestStepCoverage:
    def _ev(self, name, ts, dur, depth, tid=1):
        return {
            "name": name, "ph": "X", "tid": tid, "ts": ts, "dur": dur,
            "args": {"depth": depth},
        }

    def test_full_coverage(self):
        events = [
            self._ev("step", 0, 100, 0),
            self._ev("data_wait", 0, 40, 1),
            self._ev("compute", 40, 58, 1),
        ]
        assert step_coverage(events) == pytest.approx(0.98)

    def test_gap_detected(self):
        events = [
            self._ev("step", 0, 100, 0),
            self._ev("compute", 0, 50, 1),
        ]
        assert step_coverage(events) == pytest.approx(0.5)

    def test_overlapping_children_not_double_counted(self):
        events = [
            self._ev("step", 0, 100, 0),
            self._ev("a", 0, 60, 1),
            self._ev("b", 40, 60, 1),
        ]
        assert step_coverage(events) == pytest.approx(1.0)

    def test_deeper_descendants_ignored(self):
        # grandchildren don't count twice and other tids don't leak in
        events = [
            self._ev("step", 0, 100, 0),
            self._ev("compute", 0, 90, 1),
            self._ev("inner", 0, 90, 2),
            self._ev("h2d", 0, 100, 1, tid=2),
        ]
        assert step_coverage(events) == pytest.approx(0.9)

    def test_no_parents_returns_none(self):
        assert step_coverage([self._ev("x", 0, 1, 0)]) is None


class TestMetricsRegistry:
    def test_counter(self):
        reg = MetricsRegistry()
        c = reg.counter("req_total", "requests")
        c.inc()
        c.inc(2)
        assert c.value == 3
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_and_labels(self):
        reg = MetricsRegistry()
        g = reg.gauge("temp", "t", labelnames=("zone",))
        g.labels("a").set(1.5)
        g.labels(zone="b").inc(2.0)
        assert g.labels("a").value == 1.5
        assert g.labels("b").value == 2.0
        with pytest.raises(ValueError):
            g.set(9.0)  # labeled metric requires .labels(...)

    def test_histogram(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "l", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        assert h.count == 3
        assert h.sum == pytest.approx(5.55)
        cum = h._default_child().cumulative()
        assert cum[0] == (0.1, 1)
        assert cum[1] == (1.0, 2)
        assert cum[-1][1] == 3
        assert h.quantile(0.5) == 1.0

    def test_get_or_create_and_kind_mismatch(self):
        reg = MetricsRegistry()
        a = reg.counter("x", "first help")
        b = reg.counter("x")
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("x")

    def test_prometheus_text_format(self):
        reg = MetricsRegistry()
        reg.counter("n_total", "count of n").inc(4)
        reg.gauge("g", "gg", labelnames=("w",)).labels("3").set(1.5)
        reg.histogram("lat_seconds", "lat", buckets=(0.5,)).observe(0.2)
        text = reg.prometheus_text()
        assert "# HELP n_total count of n" in text
        assert "# TYPE n_total counter" in text
        assert "n_total 4" in text
        assert 'g{w="3"} 1.5' in text
        assert 'lat_seconds_bucket{le="0.5"} 1' in text
        assert 'lat_seconds_bucket{le="+Inf"} 1' in text
        assert "lat_seconds_count 1" in text

    def test_scalars_flat_export(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        reg.histogram("h", buckets=(1.0,)).observe(0.5)
        s = reg.scalars()
        assert s["c"] == 1.0
        assert s["h_sum"] == 0.5
        assert s["h_count"] == 1.0


class TestPipelineStatsTripwire:
    """Every PipelineStats dataclass field MUST appear in as_dict() AND
    in the registry export — fields silently missing from telemetry is
    exactly the drift mode PR 3 hit (new fields needed manual as_dict
    edits)."""

    def _stats_all_set(self):
        from dlrover_tpu.accel.profiler import PipelineStats

        stats = PipelineStats()
        for i, f in enumerate(dataclasses.fields(PipelineStats)):
            setattr(stats, f.name, float(i + 1))
        return stats

    def test_every_field_in_as_dict(self):
        from dlrover_tpu.accel.profiler import PipelineStats

        stats = self._stats_all_set()
        d = stats.as_dict()
        missing = [
            f.name
            for f in dataclasses.fields(PipelineStats)
            if f.name not in d
        ]
        assert not missing, (
            f"PipelineStats fields missing from as_dict(): {missing} — "
            f"add them or telemetry silently loses them"
        )

    def test_every_field_reaches_registry_export(self):
        from dlrover_tpu.accel.profiler import PipelineStats

        stats = self._stats_all_set()
        reg = MetricsRegistry()
        fold_pipeline_stats(stats, reg)
        scalars = reg.scalars()
        missing = [
            f.name
            for f in dataclasses.fields(PipelineStats)
            if PIPELINE_PREFIX + f.name not in scalars
        ]
        assert not missing, (
            f"PipelineStats fields missing from the registry export: "
            f"{missing}"
        )

    def test_none_fields_still_export(self):
        from dlrover_tpu.accel.profiler import PipelineStats

        reg = MetricsRegistry()
        fold_pipeline_stats(PipelineStats(), reg)  # defaults incl. None
        assert PIPELINE_PREFIX + "comm_overlap_pct" in reg.scalars()


class TestTelemetryAggregator:
    def _feed_steady(self, agg, worker, step_s, n=8, t0=1000.0):
        for i in range(n):
            agg.observe_step_report(worker, i + 1, t0 + (i + 1) * step_s)

    def test_derived_step_times(self):
        agg = TelemetryAggregator(min_samples=4)
        self._feed_steady(agg, 0, 0.1)
        assert agg.worker_p50(0) == pytest.approx(0.1, rel=0.01)

    def test_explicit_step_time_preferred(self):
        agg = TelemetryAggregator(min_samples=2)
        self._feed_steady(agg, 0, 5.0)  # coarse derived samples
        for _ in range(4):
            agg.observe_metrics(0, 10, {"step_time_ms": 100.0})
        # the explicit channel replaced the derived history entirely
        assert agg.worker_p50(0) == pytest.approx(0.1)

    def test_straggler_flags_exactly_the_inflated_worker(self):
        """One worker 3x slower than the fleet → exactly that worker is
        flagged and the brain reporter fires once."""
        reports = []
        agg = TelemetryAggregator(
            straggler_ratio=2.0,
            min_samples=4,
            brain_reporter=lambda w, p50, med: reports.append(w),
        )
        for w in range(4):
            self._feed_steady(agg, w, 0.3 if w == 3 else 0.1)
        assert agg.detect_stragglers() == [3]
        assert agg.stragglers == [3]
        assert reports == [3]
        # re-detection does not re-report while still flagged
        agg.detect_stragglers()
        assert reports == [3]

    def test_straggler_signal_reaches_brain_ingestion(self):
        """The acceptance path: detector → straggler_sink → Brain
        datastore node_events rows (event='straggler')."""
        from dlrover_tpu.brain.ingestion import straggler_sink
        from dlrover_tpu.brain.service import BrainServicer

        brain = BrainServicer(db_path=":memory:")
        try:
            agg = TelemetryAggregator(
                straggler_ratio=2.0,
                min_samples=4,
                brain_reporter=straggler_sink(brain, "job-a"),
            )
            for w in range(4):
                self._feed_steady(agg, w, 0.3 if w == 3 else 0.1)
            assert agg.detect_stragglers() == [3]
            rows = brain.node_events(job="job-a", event="straggler")
            assert [r.node_id for r in rows] == [3]
        finally:
            brain.close()

    def test_straggler_recovery_unflags_and_can_reflag(self):
        reports = []
        agg = TelemetryAggregator(
            straggler_ratio=2.0,
            min_samples=4,
            window=8,
            brain_reporter=lambda w, p50, med: reports.append(w),
        )
        for w in range(4):
            self._feed_steady(agg, w, 0.3 if w == 3 else 0.1)
        assert agg.detect_stragglers() == [3]
        # worker 3 recovers: fresh fast samples displace the window
        self._feed_steady(agg, 3, 0.1, n=8, t0=5000.0)
        assert agg.detect_stragglers() == []
        assert agg.stragglers == []
        # relapse reports again
        self._feed_steady(agg, 3, 0.3, n=8, t0=9000.0)
        assert agg.detect_stragglers() == [3]
        assert reports == [3, 3]

    def test_no_flag_below_min_samples_or_single_worker(self):
        agg = TelemetryAggregator(min_samples=4)
        self._feed_steady(agg, 0, 0.1, n=2)
        assert agg.detect_stragglers() == []
        agg2 = TelemetryAggregator(min_samples=4)
        self._feed_steady(agg2, 0, 0.3)
        assert agg2.detect_stragglers() == []  # no fleet to compare

    def test_hang_attribution_carries_last_open_span(self):
        agg = TelemetryAggregator()
        agg.observe_metrics(
            3, 50, {}, open_span="ckpt_commit", open_span_elapsed_s=42.0
        )
        name, elapsed = agg.last_open_span(3)
        assert name == "ckpt_commit"
        assert elapsed >= 42.0
        att = agg.hang_attribution()
        assert "stuck in ckpt_commit for 42" in att[3]
        assert "ckpt_commit" in agg.describe_hang()

    def test_empty_open_span_clears_attribution(self):
        agg = TelemetryAggregator()
        agg.observe_metrics(1, 5, {}, open_span="eval",
                            open_span_elapsed_s=1.0)
        agg.observe_metrics(1, 6, {}, open_span="")
        assert agg.last_open_span(1) is None

    def test_remove_worker_drops_history(self):
        agg = TelemetryAggregator(min_samples=4)
        self._feed_steady(agg, 0, 0.1)
        agg.remove_worker(0)
        assert agg.worker_p50(0) is None
        assert agg.workers() == []

    def test_export_to_registry(self):
        agg = TelemetryAggregator(min_samples=4)
        self._feed_steady(agg, 0, 0.1)
        self._feed_steady(agg, 1, 0.1)
        reg = MetricsRegistry()
        agg.export(reg)
        s = reg.scalars()
        assert s['dlrover_worker_step_time_p50_seconds{worker="0"}'] == (
            pytest.approx(0.1, rel=0.01)
        )
        assert "dlrover_fleet_step_time_median_seconds" in s
        assert s["dlrover_straggler_count"] == 0.0

    def test_export_prunes_departed_workers(self):
        """A scaled-away worker's labeled gauge child must not keep
        exposing its last p50 as a frozen ghost series."""
        agg = TelemetryAggregator(min_samples=4)
        self._feed_steady(agg, 0, 0.1)
        self._feed_steady(agg, 5, 0.1)
        reg = MetricsRegistry()
        agg.export(reg)
        assert 'dlrover_worker_step_time_p50_seconds{worker="5"}' in (
            reg.scalars()
        )
        agg.remove_worker(5)
        agg.export(reg)
        s = reg.scalars()
        assert 'dlrover_worker_step_time_p50_seconds{worker="5"}' not in s
        assert 'dlrover_worker_step_time_p50_seconds{worker="0"}' in s


class TestMasterTelemetryWiring:
    """The hooks: GlobalStepReport → SpeedMonitor(node_id) → aggregator;
    TrainMetricsReport → aggregator; auto-scaler surfaces the flags."""

    def _servicer(self):
        from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
        from dlrover_tpu.master.servicer import MasterServicer

        agg = TelemetryAggregator(straggler_ratio=2.0, min_samples=4)
        sm = SpeedMonitor(telemetry=agg)
        servicer = MasterServicer(speed_monitor=sm, telemetry=agg)
        return servicer, sm, agg

    def _report(self, servicer, message, node_id=0):
        from dlrover_tpu.common import comm

        req = comm.BaseRequest(
            node_id=node_id, data=comm.serialize_message(message)
        )
        resp = comm.deserialize_message(
            servicer.report(comm.serialize_message(req))
        )
        assert resp.success, resp.message

    def test_step_reports_feed_per_worker_samples(self):
        from dlrover_tpu.common import comm

        servicer, sm, agg = self._servicer()
        t0 = 1000.0
        for w in range(2):
            step_s = 0.3 if w == 1 else 0.1
            for i in range(8):
                self._report(
                    servicer,
                    comm.GlobalStepReport(
                        node_id=w, step=i + 1,
                        timestamp=t0 + (i + 1) * step_s,
                    ),
                    node_id=w,
                )
        assert agg.worker_p50(0) == pytest.approx(0.1, rel=0.01)
        assert agg.worker_p50(1) == pytest.approx(0.3, rel=0.01)
        # the fleet-max channel still works
        assert sm.completed_global_step == 8

    def test_train_metrics_report_carries_open_span(self):
        from dlrover_tpu.common import comm

        servicer, _, agg = self._servicer()
        self._report(
            servicer,
            comm.TrainMetricsReport(
                node_id=3, step=7, metrics={"loss": 1.0},
                open_span="ckpt_commit", open_span_elapsed_s=42.0,
            ),
            node_id=3,
        )
        assert agg.last_open_span(3)[0] == "ckpt_commit"

    def test_master_flags_3x_straggler_and_scaler_surfaces_it(self):
        """Acceptance: 4 workers report steps through the real master
        wiring, worker 2's step times inflated 3x → the auto-scaler's
        detection pass flags exactly worker 2."""
        from dlrover_tpu.common import comm
        from dlrover_tpu.master.local_master import LocalJobMaster

        master = LocalJobMaster(node_num=4)
        try:
            master.telemetry.straggler_ratio = 2.0
            t0 = 1000.0
            for w in range(4):
                step_s = 0.3 if w == 2 else 0.1
                for i in range(8):
                    master.speed_monitor.collect_global_step(
                        i + 1, t0 + (i + 1) * step_s, node_id=w
                    )
            assert master.auto_scaler.check_stragglers() == [2]
            assert master.auto_scaler.stragglers == [2]
            # hang report names the per-worker state
            master.telemetry.observe_metrics(
                2, 8, {}, open_span="grad_sync_probe",
                open_span_elapsed_s=30.0,
            )
            desc = master.telemetry.describe_hang()
            assert "worker 2 stuck in grad_sync_probe" in desc
            assert "stragglers=[2]" in desc
        finally:
            master.stop()


class TestMonitorSatellites:
    def test_report_runtime_metrics_bare_filename(
        self, tmp_path, monkeypatch
    ):
        """os.makedirs(os.path.dirname('metrics.json')) used to raise
        FileNotFoundError on the empty dirname."""
        from dlrover_tpu.agent.monitor import (
            read_runtime_metrics,
            report_runtime_metrics,
        )

        monkeypatch.chdir(tmp_path)
        report_runtime_metrics(3, path="metrics.json", loss=1.25)
        got = read_runtime_metrics("metrics.json")
        assert got["global_step"] == 3
        assert got["loss"] == 1.25

    def test_speed_monitor_honors_explicit_zero_timestamp(self):
        """`timestamp or time.time()` treated an explicit 0.0 as 'not
        provided'; the contract is `is None`."""
        from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor

        sm = SpeedMonitor(window=8)
        sm.collect_global_step(5, timestamp=0.0)
        assert sm.first_step_time == 0.0
        assert list(sm._samples) == [(0.0, 5)]
        # None still means "stamp now"
        sm2 = SpeedMonitor(window=8)
        before = time.time()
        sm2.collect_global_step(5)
        assert sm2.first_step_time >= before

    class _FakeClient:
        def __init__(self):
            self.steps = []
            self.metric_calls = []

        def report_global_step(self, step):
            self.steps.append(step)

        def report_train_metrics(
            self, step, metrics, open_span="", open_span_elapsed_s=0.0
        ):
            self.metric_calls.append(
                (step, dict(metrics), open_span, open_span_elapsed_s)
            )

    def test_training_monitor_forwards_updated_scalars_same_step(
        self, tmp_path, monkeypatch
    ):
        """A fresh loss at an UNCHANGED global step (post-restore
        refresh) must still reach the master: forwarding is gated on
        the payload timestamp, not the step."""
        from dlrover_tpu.agent.monitor import (
            TrainingMonitor,
            report_runtime_metrics,
        )

        path = str(tmp_path / "metrics.json")
        monkeypatch.setenv("DLROVER_TPU_RUNTIME_METRICS_PATH", path)
        client = self._FakeClient()
        mon = TrainingMonitor(client, interval=999)

        report_runtime_metrics(5, loss=2.0)
        mon._tick()
        assert client.steps == [5]
        assert client.metric_calls[-1][1]["loss"] == 2.0

        time.sleep(0.01)  # a distinct payload timestamp
        report_runtime_metrics(5, loss=1.5)  # same step, fresh loss
        mon._tick()
        assert client.steps == [5]  # step channel fires once
        assert client.metric_calls[-1][1]["loss"] == 1.5

        mon._tick()  # no new payload → no forward
        assert len(client.metric_calls) == 2

    def test_training_monitor_forwards_span_heartbeat_while_stuck(
        self, tmp_path, monkeypatch
    ):
        """The wedged-step path: the step stops advancing, the trainer
        stops writing — the SpanHeartbeat's file updates must still
        flow to the master (this is what makes hang reports
        attributable)."""
        from dlrover_tpu.agent.monitor import (
            TrainingMonitor,
            report_runtime_metrics,
        )

        path = str(tmp_path / "metrics.json")
        monkeypatch.setenv("DLROVER_TPU_RUNTIME_METRICS_PATH", path)
        client = self._FakeClient()
        mon = TrainingMonitor(client, interval=999)
        report_runtime_metrics(7, loss=1.0)
        mon._tick()

        tracer = SpanTracer(enabled=True)
        hb = SpanHeartbeat(tracer=tracer, path=path)
        sp = tracer.span("ckpt_commit")  # the loop "wedges" here
        try:
            time.sleep(0.01)
            hb.publish_once()
        finally:
            sp.end()
        mon._tick()
        step, metrics, open_span, elapsed = client.metric_calls[-1]
        assert step == 7
        assert open_span == "ckpt_commit"
        assert elapsed > 0


@pytest.fixture(scope="class")
def traced_smoke_run(tmp_path_factory):
    """One tiny training run with tracing on: the Chrome-trace artifact
    + the runtime-metrics payload the class below validates."""
    import jax
    import optax

    from dlrover_tpu.accel.strategy import Strategy
    from dlrover_tpu.models import tiny
    from dlrover_tpu.parallel.mesh import MeshConfig
    from dlrover_tpu.trainer.elastic.trainer import (
        ElasticTrainer,
        TrainerConfig,
    )

    tmp = tmp_path_factory.mktemp("traced_run")
    metrics_path = str(tmp / "runtime_metrics.json")
    old_env = os.environ.get("DLROVER_TPU_RUNTIME_METRICS_PATH")
    os.environ["DLROVER_TPU_RUNTIME_METRICS_PATH"] = metrics_path

    class _Tokens:
        def __init__(self, n=256, seq=32, vocab=256):
            rng = np.random.default_rng(3)
            self.data = rng.integers(
                0, vocab, (n, seq + 1), dtype=np.int32
            )

        def __len__(self):
            return len(self.data)

        def __getitem__(self, i):
            return {"x": self.data[i][:-1], "y": self.data[i][1:]}

    tracer = obs_trace.get_tracer()
    was_enabled = tracer.enabled
    tracer.enabled = True
    trainer = ElasticTrainer(
        model_cfg=tiny(num_layers=1),
        tx=optax.adamw(1e-2),
        dataset=_Tokens(),
        trainer_cfg=TrainerConfig(
            batch_size=8,
            seq_len=32,
            report_metrics=True,
            log_interval=4,
            prefetch=2,
            donation_aware=False,
            speculative_compile=False,
            ckpt_dir=str(tmp / "ckpt"),
            save_memory_interval=6,
            save_storage_interval=10_000,
        ),
        strategy=Strategy(mesh=MeshConfig(dp=1), dtype="float32"),
        devices=list(jax.devices())[:1],
    )
    try:
        trainer.train(num_steps=2)  # compile outside the traced window
        tracer.reset()
        trainer.train(num_steps=14)
        trace_path = str(tmp / "trace.json")
        tracer.dump(trace_path)
        yield {
            "trace_path": trace_path,
            "metrics_path": metrics_path,
            "stats": trainer.pipeline_stats,
        }
    finally:
        trainer.close()
        tracer.enabled = was_enabled
        if old_env is None:
            os.environ.pop("DLROVER_TPU_RUNTIME_METRICS_PATH", None)
        else:
            os.environ["DLROVER_TPU_RUNTIME_METRICS_PATH"] = old_env


class TestTrainerTraceArtifact:
    """Acceptance: a smoke training run dumps Chrome-trace JSON whose
    step spans are >= 95% explained by phase children; the registry
    scalars reach the runtime-metrics file."""

    def test_artifact_is_valid_chrome_trace(self, traced_smoke_run):
        loaded = json.load(open(traced_smoke_run["trace_path"]))
        ok, reason = validate_chrome_trace(loaded)
        assert ok, reason

    def test_step_spans_cover_95_pct(self, traced_smoke_run):
        loaded = json.load(open(traced_smoke_run["trace_path"]))
        cov = step_coverage(loaded)
        assert cov is not None
        assert cov >= 0.95, f"step phase coverage {cov:.1%} < 95%"

    def test_expected_phases_present(self, traced_smoke_run):
        loaded = json.load(open(traced_smoke_run["trace_path"]))
        names = {
            e["name"]
            for e in loaded["traceEvents"]
            if e["ph"] == "X"
        }
        for expected in (
            "step", "data_wait", "compute", "host_sync", "ckpt_save",
            "prefetch_pull", "h2d",
        ):
            assert expected in names, f"missing span {expected}"

    def test_registry_scalars_reach_metrics_file(self, traced_smoke_run):
        payload = json.load(open(traced_smoke_run["metrics_path"]))
        assert payload["global_step"] >= 12
        assert payload["step_time_ms"] > 0
        assert "loss" in payload
        # the PipelineStats fold rides the same export
        assert PIPELINE_PREFIX + "prefetch_hits" in payload
        assert "dlrover_step_time_seconds_count" in payload
