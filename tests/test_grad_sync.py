"""Overlap-scheduled gradient sync (parallel/grad_sync.py) + the
satellite fixes that ride with it: fp32 microbatch accumulation,
grad_accum equivalence, fused grad-norm, PipelineStats coverage,
dry-runner comm terms, strategy/opt_lib plumbing."""

import re
from dataclasses import replace as dc_replace

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import tiny
from dlrover_tpu.models.train import (
    build_train_step,
    init_sharded_state,
    shard_batch,
)
from dlrover_tpu.parallel.grad_sync import (
    BucketPlan,
    ensure_residual,
    plan_buckets,
    resolve_plan,
    strip_residual,
    sync_grads,
    zero_residual,
)
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh


def _mesh(n=2):
    return build_mesh(MeshConfig(dp=n), devices=jax.devices()[:n])


def _batch(cfg, batch=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)


def _fp32_tiny(**kw):
    return dc_replace(
        tiny(num_layers=1), dtype="float32", param_dtype="float32", **kw
    )


# -- bucket planning --------------------------------------------------------
class TestBucketPlan:
    def test_partitions_whole_tree_in_order(self):
        shapes = {
            "a": jax.ShapeDtypeStruct((100,), jnp.float32),
            "b": jax.ShapeDtypeStruct((300,), jnp.float32),
            "c": jax.ShapeDtypeStruct((50,), jnp.float32),
        }
        plan = plan_buckets(shapes, dp=2, bucket_bytes=1200)
        # leaves cover [0, 3) contiguously, no gaps or overlap
        spans = [(b.start, b.stop) for b in plan.buckets]
        assert spans[0][0] == 0 and spans[-1][1] == 3
        for (s0, e0), (s1, e1) in zip(spans, spans[1:]):
            assert e0 == s1
        assert sum(b.elems for b in plan.buckets) == 450

    def test_bucket_size_target_and_padding(self):
        shapes = [jax.ShapeDtypeStruct((101,), jnp.float32)] * 8
        plan = plan_buckets(shapes, dp=4, bucket_bytes=2 * 101 * 4)
        assert plan.num_buckets == 4  # two leaves per bucket
        for b in plan.buckets:
            assert b.elems == 202
            assert b.padded % 4 == 0 and b.padded >= b.elems

    def test_oversized_leaf_gets_own_bucket(self):
        shapes = [
            jax.ShapeDtypeStruct((10,), jnp.float32),
            jax.ShapeDtypeStruct((10_000,), jnp.float32),
            jax.ShapeDtypeStruct((10,), jnp.float32),
        ]
        plan = plan_buckets(shapes, dp=2, bucket_bytes=1024)
        big = [b for b in plan.buckets if b.elems == 10_000]
        assert len(big) == 1

    def test_wire_accounting_int8_vs_raw(self):
        shapes = [jax.ShapeDtypeStruct((1000,), jnp.float32)] * 4
        raw = plan_buckets(shapes, dp=2, bucket_bytes=1 << 20)
        q = plan_buckets(
            shapes, dp=2, bucket_bytes=1 << 20, compress="int8"
        )
        assert raw.wire_bytes == raw.raw_bytes == 16_000
        # 1 byte/elem + 4-byte scale per bucket: ~25% of fp32
        assert q.raw_bytes == 16_000
        assert q.wire_bytes <= 0.30 * q.raw_bytes

    def test_rejects_unknown_compression(self):
        with pytest.raises(ValueError, match="compression"):
            plan_buckets(
                [jax.ShapeDtypeStruct((4,), jnp.float32)],
                dp=2,
                compress="fp4",
            )


# -- sync_grads unit level --------------------------------------------------
class TestSyncGrads:
    def _stacked(self, mesh, dp, tree):
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(mesh, P(("dp",)))
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sh), tree
        )

    def test_fp32_sync_is_exact_mean_multi_bucket(self):
        mesh = _mesh(2)
        rng = np.random.default_rng(0)
        tree = {
            "w": rng.standard_normal((2, 64, 3)).astype(np.float32),
            "b": rng.standard_normal((2, 37)).astype(np.float32),
        }
        shapes = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), tree
        )
        # force >1 bucket so bucket boundaries are exercised
        plan = plan_buckets(shapes, dp=2, bucket_bytes=256)
        assert plan.num_buckets > 1
        stacked = self._stacked(mesh, 2, tree)
        synced, res, gnorm = jax.jit(
            lambda t: sync_grads(t, mesh, plan)
        )(stacked)
        ref = jax.tree_util.tree_map(lambda a: a.mean(axis=0), tree)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(synced[k]), ref[k], atol=1e-6
            )
        assert res is None
        ref_norm = float(
            np.sqrt(sum(float((ref[k] ** 2).sum()) for k in ref))
        )
        assert abs(float(gnorm) - ref_norm) < 1e-4

    def test_int8_error_bounded_and_residual_carries(self):
        mesh = _mesh(2)
        rng = np.random.default_rng(1)
        tree = {"w": rng.standard_normal((2, 500)).astype(np.float32)}
        shapes = {"w": jax.ShapeDtypeStruct((500,), jnp.float32)}
        plan = plan_buckets(
            shapes, dp=2, bucket_bytes=1 << 20, compress="int8"
        )
        stacked = self._stacked(mesh, 2, tree)
        res0 = zero_residual(plan, mesh)
        synced, res1, _ = jax.jit(
            lambda t, r: sync_grads(t, mesh, plan, residual=r)
        )(stacked, res0)
        ref = tree["w"].mean(axis=0)
        # per-device rounding error <= scale/2 per element; the mean
        # keeps that bound
        scale = np.abs(tree["w"]).max() / 127.0
        assert float(np.abs(np.asarray(synced["w"]) - ref).max()) <= (
            scale / 2 + 1e-6
        )
        # the dropped quantization error is exactly the new residual
        assert res1 is not None and len(res1) == plan.num_buckets
        assert float(np.abs(np.asarray(res1[0])).max()) > 0

    def test_int8_without_residual_is_structure_preserving(self):
        mesh = _mesh(2)
        tree = {"w": np.ones((2, 16), np.float32)}
        shapes = {"w": jax.ShapeDtypeStruct((16,), jnp.float32)}
        plan = plan_buckets(
            shapes, dp=2, bucket_bytes=1 << 20, compress="int8"
        )
        stacked = self._stacked(mesh, 2, tree)
        synced, res, _ = jax.jit(
            lambda t: sync_grads(t, mesh, plan, residual=None)
        )(stacked)
        assert res is None
        np.testing.assert_allclose(
            np.asarray(synced["w"]), np.ones(16), atol=1e-2
        )


# -- train-step integration -------------------------------------------------
class TestTrainStepSync:
    def test_overlap_matches_gspmd_exactly(self):
        cfg = _fp32_tiny()
        mesh = _mesh(2)
        tx = optax.adamw(1e-2)
        state, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, mesh, tx
        )
        x = _batch(cfg)
        b = shard_batch({"x": x, "y": x}, mesh)
        base = build_train_step(cfg, mesh, tx, donate=False)
        sync = build_train_step(
            cfg, mesh, tx, donate=False, comm_overlap=True
        )
        s0, m0 = base(state, b["x"], b["y"])
        s1, m1 = sync(state, b["x"], b["y"])
        assert abs(float(m0["loss"]) - float(m1["loss"])) < 1e-5
        # the fused bucket-walk grad norm replaces optax.global_norm
        assert abs(
            float(m0["grad_norm"]) - float(m1["grad_norm"])
        ) < 1e-4
        for a, c in zip(
            jax.tree_util.tree_leaves(s0.params),
            jax.tree_util.tree_leaves(s1.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(c), atol=1e-5
            )

    def test_grad_accum_syncs_once_per_step(self):
        """The K× wire saving: under grad_accum=K the explicit path
        accumulates LOCAL grads and issues each bucket's collective
        exactly once per optimizer step — asserted on the lowered HLO
        (one reduce_scatter per bucket, none inside the scan)."""
        cfg = _fp32_tiny()
        mesh = _mesh(2)
        tx = optax.adamw(1e-2)
        state, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, mesh, tx
        )
        x = _batch(cfg)
        b = shard_batch({"x": x, "y": x}, mesh)
        plan = resolve_plan(
            cfg,
            __import__(
                "dlrover_tpu.accel.strategy", fromlist=["Strategy"]
            ).Strategy(
                mesh=MeshConfig(dp=2), comm_overlap=True
            ),
        )
        for k in (1, 4):
            step = build_train_step(
                cfg, mesh, tx, donate=False,
                comm_overlap=True, grad_accum=k,
            )
            txt = step.lower(state, b["x"], b["y"]).as_text()
            n_rs = len(re.findall(r"reduce_scatter", txt))
            assert n_rs == plan.num_buckets, (
                f"grad_accum={k}: {n_rs} reduce_scatters vs "
                f"{plan.num_buckets} buckets — sync must run exactly "
                f"once per optimizer step"
            )

    # slow tier (budget): the ga-sync *structure* is tier-1-covered by
    # test_grad_accum_syncs_once_per_step (HLO) and its semantics by
    # TestGradAccumEquivalence; this cross-checks the two combined
    @pytest.mark.slow
    def test_grad_accum_sync_numerics(self):
        cfg = _fp32_tiny()
        mesh = _mesh(2)
        tx = optax.adamw(1e-2)
        state, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, mesh, tx
        )
        x = _batch(cfg)
        b = shard_batch({"x": x, "y": x}, mesh)
        s1, m1 = build_train_step(
            cfg, mesh, tx, donate=False, comm_overlap=True
        )(state, b["x"], b["y"])
        s4, m4 = build_train_step(
            cfg, mesh, tx, donate=False, comm_overlap=True,
            grad_accum=4,
        )(state, b["x"], b["y"])
        assert abs(float(m1["loss"]) - float(m4["loss"])) < 1e-5
        for a, c in zip(
            jax.tree_util.tree_leaves(s1.params),
            jax.tree_util.tree_leaves(s4.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(c), atol=2e-5
            )

    def test_int8_error_feedback_convergence_parity(self):
        """The bench gate in test form: int8+EF training tracks the
        fp32 baseline's loss on the same data/init."""
        cfg = _fp32_tiny()
        mesh = _mesh(2)
        tx = optax.adamw(1e-2)
        x = _batch(cfg, batch=8, seq=16)
        b = shard_batch({"x": x, "y": x}, mesh)

        def run(compress):
            state, _ = init_sharded_state(
                jax.random.PRNGKey(0), cfg, mesh, tx
            )
            step = build_train_step(
                cfg, mesh, tx, donate=False, comm_overlap=True,
                grad_compress=compress, grad_bucket_mb=1,
            )
            if compress == "int8":
                plan = plan_buckets(
                    jax.eval_shape(lambda: state.params),
                    dp=2, bucket_bytes=1 << 20, compress="int8",
                )
                state = ensure_residual(state, plan, mesh)
            for _ in range(12):
                state, m = step(state, b["x"], b["y"])
            return float(m["loss"]), state

        loss_fp32, _ = run("none")
        loss_int8, s8 = run("int8")
        assert abs(loss_int8 - loss_fp32) < 0.05
        # residual persisted across steps (the EF state is live)
        assert s8.grad_residual is not None

    def test_donating_twin_keeps_the_explicit_sync(self):
        """auto_accelerate strategies carry the grad-sync knobs as
        un-applied opt NAMES; the donating twin must resolve them the
        same way the primary step does, or donated steps silently run
        the GSPMD sync (and skip the error-feedback update)."""
        from dlrover_tpu.accel.accelerate import auto_accelerate
        from dlrover_tpu.accel.strategy import Strategy

        cfg = _fp32_tiny()
        tx = optax.adamw(1e-2)
        res = auto_accelerate(
            cfg, tx, batch=8, seq=16,
            devices=jax.devices()[:2],
            strategy=Strategy(mesh=MeshConfig(dp=2), dtype="float32"),
            donate=False,
            optimizations=("grad_compress",),
        )
        assert res.donating_step_fn is not None
        # knobs arrived as opt names, not fields
        assert res.strategy.comm_overlap is False
        assert "grad_compress" in res.strategy.opts
        state, _ = init_sharded_state(
            jax.random.PRNGKey(0), res.cfg, res.mesh, tx
        )
        plan = resolve_plan(res.cfg, res.strategy)
        state = ensure_residual(state, plan, res.mesh)
        x = _batch(cfg)
        b = shard_batch({"x": x, "y": x}, res.mesh)
        for fn in (res.step_fn, res.donating_step_fn):
            txt = fn.lower(state, b["x"], b["y"]).as_text()
            assert len(re.findall(r"reduce_scatter", txt)) == (
                plan.num_buckets
            )

    def test_unsupported_mesh_falls_back(self):
        """pp/ep candidates must still build when comm_overlap is
        stamped across the whole candidate list (fsdp and tp meshes
        now take the explicit path — tests/test_hybrid_sync.py)."""
        cfg = _fp32_tiny()
        mesh = build_mesh(
            MeshConfig(pp=2), devices=jax.devices()[:2]
        )
        tx = optax.adamw(1e-2)
        state, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, mesh, tx
        )
        x = _batch(cfg)
        b = shard_batch({"x": x, "y": x}, mesh)
        step = build_train_step(
            cfg, mesh, tx, donate=False, comm_overlap=True
        )
        _, m = step(state, b["x"], b["y"])
        assert np.isfinite(float(m["loss"]))


# -- satellite: fp32 accumulation under grad_accum --------------------------
def _bf16_ga_fixture():
    cfg = dc_replace(
        tiny(num_layers=1),
        dtype="bfloat16",
        param_dtype="bfloat16",
    )
    mesh = build_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    tx = optax.sgd(1.0)
    state, _ = init_sharded_state(jax.random.PRNGKey(0), cfg, mesh, tx)
    x = _batch(cfg)
    b = shard_batch({"x": x, "y": x}, mesh)
    return cfg, mesh, tx, state, b


class TestFp32Accumulation:
    def test_bf16_params_accumulate_in_fp32_hlo(self):
        """build_train_step used to seed the scan carry with
        zeros_like(params): bf16 params accumulated microbatch grads
        in bf16, losing low bits every add. The carry must be fp32 —
        visible in the lowered HLO as param-shaped f32 accumulators
        (lower-only: no compile, so this regression tripwire stays
        tier-1-cheap; the numeric cross-check is the slow twin)."""
        cfg, mesh, tx, state, b = _bf16_ga_fixture()
        step = build_train_step(
            cfg, mesh, tx, donate=False, grad_accum=4
        )
        txt = step.lower(state, b["x"], b["y"]).as_text()
        acc_shape = f"tensor<{cfg.vocab_size}x{cfg.model_dim}xf32>"
        assert acc_shape in txt, (
            "grad_accum scan must carry fp32 accumulators for bf16 "
            "params (none found in the lowered HLO)"
        )

    @pytest.mark.slow
    def test_bf16_params_fp32_accumulation_numerics(self):
        """Numeric twin of the HLO check: the ga step must match an
        explicit fp32-accumulate-then-cast reference."""
        from dlrover_tpu.models.transformer import loss_fn

        cfg, mesh, tx, state, b = _bf16_ga_fixture()
        x = np.asarray(b["x"])
        K = 4
        step = build_train_step(
            cfg, mesh, tx, donate=False, grad_accum=K
        )
        s_new, _ = step(state, b["x"], b["y"])
        mb = x.shape[0] // K
        acc = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params
        )
        for i in range(K):
            g = jax.grad(
                lambda q: loss_fn(
                    q,
                    b["x"][i * mb : (i + 1) * mb],
                    b["y"][i * mb : (i + 1) * mb],
                    cfg,
                    mesh,
                )
            )(state.params)
            acc = jax.tree_util.tree_map(
                lambda a, gg: a + gg.astype(jnp.float32), acc, g
            )
        ref = jax.tree_util.tree_map(
            lambda a, p: (a / K).astype(p.dtype), acc, state.params
        )
        got = jax.tree_util.tree_map(
            lambda p0, p1: p0 - p1, state.params, s_new.params
        )
        for a, c in zip(
            jax.tree_util.tree_leaves(got),
            jax.tree_util.tree_leaves(ref),
        ):
            # sgd(1.0): update == grads, modulo ONE bf16 apply round
            np.testing.assert_allclose(
                np.asarray(a, np.float32),
                np.asarray(c, np.float32),
                atol=2e-2,
            )


# -- satellite: grad_accum equivalence (default GSPMD path) -----------------
class TestGradAccumEquivalence:
    def test_ga4_matches_ga1_fp32(self):
        cfg = _fp32_tiny()
        mesh = _mesh(2)
        tx = optax.adamw(1e-2)
        state, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, mesh, tx
        )
        x = _batch(cfg)
        b = shard_batch({"x": x, "y": x}, mesh)
        s1, m1 = build_train_step(cfg, mesh, tx, donate=False)(
            state, b["x"], b["y"]
        )
        s4, m4 = build_train_step(
            cfg, mesh, tx, donate=False, grad_accum=4
        )(state, b["x"], b["y"])
        assert abs(float(m1["loss"]) - float(m4["loss"])) < 1e-5
        for a, c in zip(
            jax.tree_util.tree_leaves(s1.params),
            jax.tree_util.tree_leaves(s4.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(c), atol=2e-5
            )


# -- satellite: PipelineStats coverage --------------------------------------
class TestPipelineStatsGradSync:
    def test_as_dict_and_summary_cover_grad_sync_fields(self):
        from dlrover_tpu.accel.profiler import PipelineStats

        st = PipelineStats(
            prefetch_hits=3,
            prefetch_misses=1,
            grad_sync_ms=2.5,
            comm_overlap_pct=70.0,
            grad_bytes_wire=25_000,
            grad_bytes_raw=100_000,
        )
        d = st.as_dict()
        assert d["grad_sync_ms"] == 2.5
        assert d["comm_overlap_pct"] == 70.0
        assert d["grad_bytes_wire_vs_raw"] == [25_000, 100_000]
        s = st.summary()
        assert "grad sync" in s and "70.0% overlapped" in s
        assert "24 KiB wire" in s

    def test_defaults_omit_grad_sync(self):
        from dlrover_tpu.accel.profiler import PipelineStats

        st = PipelineStats()
        d = st.as_dict()
        assert d["grad_bytes_wire_vs_raw"] is None
        assert d["comm_overlap_pct"] is None
        assert "grad sync" not in st.summary()
        # round-trippable: every as_dict value is JSON-serializable
        import json

        json.dumps(d)


# -- strategy / opt_lib / dry_runner plumbing -------------------------------
class TestStrategyPlumbing:
    def test_json_roundtrip_with_grad_sync_fields(self):
        from dlrover_tpu.accel.strategy import Strategy

        s = Strategy(
            mesh=MeshConfig(dp=4),
            comm_overlap=True,
            grad_compress="int8",
            grad_bucket_mb=8,
        )
        s2 = Strategy.from_json(s.to_json())
        assert s2 == s
        assert "comm_overlap" in s.describe()
        assert "int8grad" in s.describe()

    def test_old_json_still_parses(self):
        import json as _json

        from dlrover_tpu.accel.strategy import Strategy

        d = _json.loads(Strategy().to_json())
        for k in ("comm_overlap", "grad_compress", "grad_bucket_mb"):
            d.pop(k)
        s = Strategy.from_json(_json.dumps(d))
        assert s.comm_overlap is False
        assert s.grad_compress == "none"

    def test_opt_lib_registrations(self):
        from dlrover_tpu.accel.opt_lib import (
            apply_optimizations,
            registered_optimizations,
        )
        from dlrover_tpu.accel.strategy import Strategy

        assert "comm_overlap" in registered_optimizations()
        assert "grad_compress" in registered_optimizations()
        cfg = tiny()
        _, s = apply_optimizations(
            cfg, Strategy(mesh=MeshConfig(dp=2)), ("grad_compress",)
        )
        # compression implies the explicit sync path
        assert s.comm_overlap and s.grad_compress == "int8"
        assert s.opts == ("grad_compress",)

    def test_resolved_accessors_honor_opts(self):
        from dlrover_tpu.accel.strategy import Strategy

        s = Strategy(mesh=MeshConfig(dp=2), opts=("grad_compress",))
        assert s.resolved_comm_overlap()
        assert s.resolved_grad_compress() == "int8"
        assert resolve_plan(tiny(num_layers=1), s) is not None

    def test_resolve_plan_gates_on_mesh(self):
        from dlrover_tpu.accel.strategy import Strategy

        cfg = tiny(num_layers=1)
        assert resolve_plan(
            cfg, Strategy(mesh=MeshConfig(dp=2))
        ) is None  # not requested
        # ISSUE 13: pp x dp and 3D meshes now get explicit plans; a
        # model that cannot pipeline at the degree (1 layer over pp=2)
        # still falls back
        assert resolve_plan(
            cfg,
            Strategy(mesh=MeshConfig(dp=2, pp=2), comm_overlap=True),
        ) is None
        from dlrover_tpu.parallel.grad_sync import PPSyncPlan

        ppp = resolve_plan(
            tiny(num_layers=2),
            Strategy(mesh=MeshConfig(dp=2, pp=2), comm_overlap=True),
        )
        assert isinstance(ppp, PPSyncPlan) and ppp.pp == 2
        d3 = resolve_plan(
            cfg,
            Strategy(
                mesh=MeshConfig(dp=2, fsdp=2, tp=2), comm_overlap=True
            ),
        )
        assert d3 is not None and d3.three_d and d3.tp == 2
        # a pp x ep composition stays GSPMD (the remaining exotica)
        assert resolve_plan(
            tiny(num_layers=2, num_experts=2),
            Strategy(
                mesh=MeshConfig(dp=2, pp=2, ep=2), comm_overlap=True
            ),
        ) is None
        plan = resolve_plan(
            cfg, Strategy(mesh=MeshConfig(dp=2), comm_overlap=True)
        )
        assert isinstance(plan, BucketPlan) and plan.dp == 2
        # dp x fsdp now plans the ZeRO schedule; dp x tp the bucketed
        # dp sync under the tp submesh (details: test_hybrid_sync.py)
        zp = resolve_plan(
            cfg,
            Strategy(mesh=MeshConfig(dp=2, fsdp=2), comm_overlap=True),
        )
        assert zp is not None and zp.zero and zp.fsdp == 2
        tpp = resolve_plan(
            cfg,
            Strategy(mesh=MeshConfig(dp=2, tp=2), comm_overlap=True),
        )
        assert tpp is not None and tpp.auto_axes == ("tp",)


class TestDryRunnerCommCost:
    def _report(self, strategy):
        from dlrover_tpu.accel.dry_runner import (
            DryRunReport,
            _comm_estimate,
        )

        r = DryRunReport(strategy=strategy, ok=True)
        _comm_estimate(r, tiny(num_layers=1), 8, 16, None)
        return r

    def test_overlap_and_compress_shrink_the_comm_term(self):
        from dlrover_tpu.accel.strategy import Strategy

        plain = self._report(
            Strategy(mesh=MeshConfig(dp=2), grad_accum=4)
        )
        overlap = self._report(
            Strategy(
                mesh=MeshConfig(dp=2), grad_accum=4, comm_overlap=True
            )
        )
        int8 = self._report(
            Strategy(
                mesh=MeshConfig(dp=2),
                grad_accum=4,
                comm_overlap=True,
                grad_compress="int8",
            )
        )
        assert plain.comm_bytes_per_device > 0
        # explicit path: one sync per step instead of per microbatch
        assert (
            overlap.comm_bytes_per_device
            < plain.comm_bytes_per_device
        )
        # + overlap credit on the exposed seconds
        assert overlap.comm_exposed_s < plain.comm_exposed_s
        # + int8 payload
        assert int8.comm_bytes_per_device < overlap.comm_bytes_per_device

    def test_single_device_has_no_comm_term(self):
        from dlrover_tpu.accel.strategy import Strategy

        r = self._report(Strategy(mesh=MeshConfig(dp=1)))
        assert r.comm_bytes_per_device == 0.0
        assert r.comm_exposed_s == 0.0

    def test_unsupported_mesh_fallback_priced_full_precision(self):
        """A pp candidate carrying the compress knob as an opt name
        falls back to GSPMD full-precision sync at runtime — the cost
        model must price it that way, not at int8 wire bytes it never
        gets."""
        from dlrover_tpu.accel.strategy import Strategy

        plain = self._report(
            Strategy(mesh=MeshConfig(dp=2, pp=2, ep=2))
        )
        compressed_opts = self._report(
            Strategy(
                mesh=MeshConfig(dp=2, pp=2, ep=2),
                opts=("grad_compress",),
            )
        )
        assert (
            compressed_opts.comm_bytes_per_device
            == plain.comm_bytes_per_device
        )

    def test_explicit_fsdp_priced_below_gspmd_allreduce(self):
        """An fsdp candidate on the explicit path is priced with the
        ZeRO schedule (reduce-scatter, no gather twin, dp legs on the
        chunk) — strictly below the monolithic all-reduce its GSPMD
        twin pays."""
        from dlrover_tpu.accel.strategy import Strategy

        gspmd = self._report(Strategy(mesh=MeshConfig(dp=2, fsdp=2)))
        explicit = self._report(
            Strategy(
                mesh=MeshConfig(dp=2, fsdp=2), comm_overlap=True
            )
        )
        assert 0 < explicit.comm_bytes_per_device
        assert (
            explicit.comm_bytes_per_device
            < gspmd.comm_bytes_per_device
        )
        assert explicit.comm_exposed_s < gspmd.comm_exposed_s


# -- residual lifecycle -----------------------------------------------------
class TestResidualLifecycle:
    def test_ensure_and_strip_are_inverse_and_idempotent(self):
        from dlrover_tpu.models.train import TrainState

        cfg = _fp32_tiny()
        mesh = _mesh(2)
        plan = plan_buckets(
            jax.eval_shape(
                lambda: __import__(
                    "dlrover_tpu.models.transformer",
                    fromlist=["init_params"],
                ).init_params(jax.random.PRNGKey(0), cfg)
            ),
            dp=2,
            compress="int8",
        )
        state = TrainState(step=0, params={}, opt_state={})
        st2 = ensure_residual(state, plan, mesh)
        assert st2.grad_residual is not None
        assert ensure_residual(st2, plan, mesh) is st2
        st3 = strip_residual(st2)
        assert st3.grad_residual is None
        assert strip_residual(st3) is st3
        # None residual contributes no leaves: old checkpoints load
        assert jax.tree_util.tree_structure(
            state
        ) == jax.tree_util.tree_structure(st3)

    def test_no_plan_is_noop(self):
        from dlrover_tpu.models.train import TrainState

        state = TrainState(step=0, params={}, opt_state={})
        assert ensure_residual(state, None, None) is state


# -- ElasticTrainer integration ---------------------------------------------
class TestTrainerGradSync:
    def test_knobs_flow_and_resize_replans_buckets(self):
        """TrainerConfig knobs → opt names → strategy → bucket plan →
        EF residual → PipelineStats; a resize re-plans for the new dp
        degree and re-seeds the residual (its shapes changed)."""
        from dlrover_tpu.trainer.elastic.trainer import (
            ElasticTrainer,
            TrainerConfig,
        )

        class _Toks:
            def __init__(self, n=64, seq=16, vocab=256):
                rng = np.random.default_rng(0)
                self.d = rng.integers(
                    0, vocab, (n, seq + 1), dtype=np.int32
                )

            def __len__(self):
                return len(self.d)

            def __getitem__(self, i):
                return {"x": self.d[i][:-1], "y": self.d[i][1:]}

        from dlrover_tpu.accel.strategy import Strategy

        tr = ElasticTrainer(
            model_cfg=tiny(num_layers=1),
            tx=optax.adamw(1e-2),
            dataset=_Toks(),
            trainer_cfg=TrainerConfig(
                batch_size=8,
                seq_len=16,
                report_metrics=False,
                log_interval=1000,
                prefetch=0,
                # donation ON: most production steps run the donating
                # twin — it must keep the explicit sync + EF update
                donation_aware=True,
                speculative_compile=False,
                comm_overlap=True,
                grad_compress="int8",
                grad_bucket_mb=1,
            ),
            strategy=Strategy(mesh=MeshConfig(dp=2), dtype="float32"),
            devices=jax.devices()[:2],
        )
        try:
            # knobs became opt names on the strategy
            assert "comm_overlap" in tr.accel.strategy.opts
            assert "grad_compress" in tr.accel.strategy.opts
            plan = tr._grad_sync_plan
            assert plan is not None and plan.dp == 2
            assert plan.compress == "int8"
            assert tr.state.grad_residual is not None
            st = tr.pipeline_stats
            assert st.grad_bytes_raw > 0
            assert st.grad_bytes_wire <= 0.30 * st.grad_bytes_raw
            assert st.comm_overlap_pct is not None
            # checkpoint trees never carry the residual
            assert (
                tr._ckpt_state()["train"].grad_residual is None
            )
            tr.train(num_steps=2)
            assert tr.state.grad_residual is not None
            # donated steps ran the compressed sync: the EF residual
            # moved off its zero seed (a GSPMD-path twin would have
            # passed it through untouched)
            assert any(
                float(jnp.sum(jnp.abs(r))) > 0
                for r in tr.state.grad_residual
            )
            assert tr.pipeline_stats.donated_steps > 0
            tr.resize(4)
            # buckets re-planned for the new world, residual re-seeded
            assert tr._grad_sync_plan.dp == 4
            assert tr.state.grad_residual is not None
            assert all(
                r.shape[0] == 4 for r in tr.state.grad_residual
            )
            tr.train(num_steps=4)
            assert tr.global_step == 4
        finally:
            tr.close()


class TestKnobPlumbing:
    def test_auto_accelerate_stamps_grad_bucket_mb(self):
        """TrainerConfig.grad_bucket_mb reaches the strategy (the
        name-only opt registry cannot carry the integer)."""
        from dlrover_tpu.accel.accelerate import auto_accelerate
        from dlrover_tpu.accel.strategy import Strategy

        res = auto_accelerate(
            _fp32_tiny(),
            optax.adamw(1e-2),
            batch=8,
            seq=16,
            devices=jax.devices()[:2],
            strategy=Strategy(mesh=MeshConfig(dp=2), dtype="float32"),
            donate=False,
            optimizations=("comm_overlap",),
            grad_bucket_mb=8,
        )
        assert res.strategy.grad_bucket_mb == 8

    def test_strategy_for_fallback_preserves_field_knobs(self):
        """A non-divisible resize takes the candidate-enumeration
        fallback; field-carried grad-sync knobs (an explicit Strategy
        without opt names) must survive it."""
        import types

        from dlrover_tpu.accel.strategy import Strategy
        from dlrover_tpu.trainer.elastic.trainer import (
            ElasticTrainer,
            TrainerConfig,
        )

        s = Strategy(
            mesh=MeshConfig(dp=2),
            dtype="float32",
            comm_overlap=True,
            grad_compress="int8",
            grad_bucket_mb=2,
        )
        fake = types.SimpleNamespace(
            accel=types.SimpleNamespace(strategy=s),
            tcfg=TrainerConfig(batch_size=6, seq_len=16),
            _model_cfg=tiny(num_layers=1),
        )
        # 6 % dp4 != 0 -> fast path rejected -> enumeration fallback
        out = ElasticTrainer._strategy_for_exact(fake, 4)
        assert out is not None
        assert out.comm_overlap is True
        assert out.grad_compress == "int8"
        assert out.grad_bucket_mb == 2


# -- bench leg (slow: three full train-step compiles + 72 steps) ------------
@pytest.mark.slow
class TestBenchGradSync:
    def test_bench_leg_emits_keys_and_passes_gates(self):
        """The --smoke gate in test form: the bench's three-way
        comparison (fp32 / bucketed / int8+EF) must emit every
        acceptance key and land inside its documented gates."""
        import importlib.util
        import os as _os

        spec = importlib.util.spec_from_file_location(
            "bench_grad_sync_mod",
            _os.path.join(
                _os.path.dirname(_os.path.dirname(__file__)), "bench.py"
            ),
        )
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        results = {}
        bench.run_grad_sync_bench(jax, results, smoke=True)
        assert results["grad_sync_ms"] > 0
        assert results["comm_overlap_pct"] is not None
        wire, raw = results["grad_bytes_wire_vs_raw"]
        assert wire <= bench.GRAD_SYNC_WIRE_GATE * raw
        # same schedule, same math: bucketed fp32 == GSPMD baseline
        assert (
            abs(
                results["grad_sync_loss_overlap"]
                - results["grad_sync_loss_fp32"]
            )
            < 1e-4
        )
        assert (
            results["grad_sync_loss_gap"] <= bench.GRAD_SYNC_LOSS_GATE
        )
