"""RLHF engine: cached generation, GAE, and PPO actually optimizing a
programmatic reward on a tiny model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import forward, init_params, tiny
from dlrover_tpu.models.transformer import forward_step, init_kv_cache
from dlrover_tpu.rl import PPOConfig, ReplayBuffer, RLHFEngine, generate
from dlrover_tpu.rl.generation import sequence_logprobs
from dlrover_tpu.rl.ppo import gae_advantages


@pytest.fixture(scope="module")
def cfg():
    return tiny(vocab_size=32, num_layers=2, max_seq_len=64)


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(0), cfg)


class TestCachedDecoding:
    def test_prefill_matches_plain_forward(self, cfg, params):
        """Cache-aware forward must agree with the plain forward
        exactly (same weights, same math)."""
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 10)),
            jnp.int32,
        )
        ref_logits, _ = forward(params, tokens, cfg)
        cache = init_kv_cache(cfg, 2, 16)
        got_logits, _ = forward_step(params, tokens, cfg, cache, 0)
        np.testing.assert_allclose(
            np.asarray(got_logits), np.asarray(ref_logits),
            rtol=2e-4, atol=2e-4,
        )

    def test_incremental_decode_matches_prefill(self, cfg, params):
        """Token-by-token decoding through the cache must equal one
        prefill over the same sequence."""
        rng = np.random.default_rng(1)
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (1, 8)), jnp.int32
        )
        cache = init_kv_cache(cfg, 1, 8)
        full_logits, _ = forward_step(params, tokens, cfg, cache, 0)

        cache = init_kv_cache(cfg, 1, 8)
        steps = []
        for i in range(8):
            logits, cache = forward_step(
                params, tokens[:, i : i + 1], cfg, cache, i
            )
            steps.append(logits[:, 0])
        np.testing.assert_allclose(
            np.asarray(jnp.stack(steps, axis=1)),
            np.asarray(full_logits),
            rtol=3e-4, atol=3e-4,
        )

    def test_generate_shapes_and_logprobs(self, cfg, params):
        prompt = jnp.zeros((3, 4), jnp.int32)
        tokens, logprobs = generate(
            params, prompt, jax.random.PRNGKey(0), cfg, max_new_tokens=6
        )
        assert tokens.shape == (3, 10) and logprobs.shape == (3, 6)
        assert np.all(np.asarray(logprobs) <= 0)
        # rollout logprobs match teacher-forced re-scoring
        rescored = sequence_logprobs(params, tokens, cfg, prompt_len=4)
        np.testing.assert_allclose(
            np.asarray(logprobs), np.asarray(rescored),
            rtol=3e-4, atol=3e-4,
        )

    def test_greedy_is_deterministic(self, cfg, params):
        prompt = jnp.zeros((2, 3), jnp.int32)
        t1, _ = generate(
            params, prompt, jax.random.PRNGKey(0), cfg,
            max_new_tokens=5, greedy=True,
        )
        t2, _ = generate(
            params, prompt, jax.random.PRNGKey(42), cfg,
            max_new_tokens=5, greedy=True,
        )
        np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))


class TestGAE:
    def test_matches_manual_recursion(self):
        rng = np.random.default_rng(0)
        rewards = jnp.asarray(rng.normal(size=(2, 5)).astype(np.float32))
        values = jnp.asarray(rng.normal(size=(2, 5)).astype(np.float32))
        gamma, lam = 0.9, 0.8
        adv, ret = gae_advantages(rewards, values, gamma, lam)
        r, v = np.asarray(rewards), np.asarray(values)
        expect = np.zeros_like(r)
        last = np.zeros(2)
        for t in range(4, -1, -1):
            v_next = v[:, t + 1] if t + 1 < 5 else 0.0
            delta = r[:, t] + gamma * v_next - v[:, t]
            last = delta + gamma * lam * last
            expect[:, t] = last
        np.testing.assert_allclose(
            np.asarray(adv), expect, rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(ret), expect + v, rtol=1e-4, atol=1e-5
        )


class TestPPO:
    # slow tier (budget): a ~14s convergence A/B; the PPO machinery
    # (advantages, ratios, clipping, rescoring) keeps tier-1 unit
    # coverage in the rest of this class
    @pytest.mark.slow
    def test_reward_improves(self, cfg):
        """PPO on a programmatic reward (emit token 7) must raise the
        expected reward of rollouts — the whole engine end to end."""
        target = 7

        def reward_fn(tokens, prompt_len):
            return (tokens[:, prompt_len:] == target).mean(axis=1) * 4.0

        engine = RLHFEngine(
            cfg,
            reward_fn,
            ppo=PPOConfig(
                rollout_batch=16,
                max_new_tokens=8,
                minibatch_size=16,
                ppo_epochs=2,
                learning_rate=5e-3,
                kl_coef=0.01,
            ),
            seed=0,
        )
        prompts = np.zeros((16, 4), dtype=np.int32)

        def mean_reward():
            toks, _ = generate(
                engine.actor_params,
                jnp.asarray(prompts),
                jax.random.PRNGKey(123),
                cfg,
                max_new_tokens=8,
            )
            return float(reward_fn(np.asarray(toks), 4).mean())

        before = mean_reward()
        for _ in range(8):
            engine.make_experience(prompts)
            metrics = engine.train(prompt_len=4)
        after = mean_reward()
        assert after > before + 0.2, (before, after, metrics)
        assert np.isfinite(metrics["loss"])


class TestRewardModel:
    def test_learns_preferences(self, cfg):
        """Bradley-Terry training: after fitting preference pairs, the
        reward head scores chosen sequences above rejected ones on
        HELD-OUT pairs."""
        from dlrover_tpu.rl.reward import RewardModel

        rng = np.random.default_rng(0)

        def make_pairs(n):
            # preference signal: "chosen" sequences are dominated by
            # token 3, "rejected" by token 11
            chosen = rng.choice([3, 4], size=(n, 12), p=[0.9, 0.1])
            rejected = rng.choice([11, 4], size=(n, 12), p=[0.9, 0.1])
            return chosen.astype(np.int32), rejected.astype(np.int32)

        rm = RewardModel(cfg, lr=1e-3, seed=0)
        c_tr, r_tr = make_pairs(64)
        for _ in range(30):
            m = rm.train_on_preferences(c_tr, r_tr)
        assert m["accuracy"] == 1.0, m
        c_te, r_te = make_pairs(32)
        assert (rm.score(c_te) > rm.score(r_te)).mean() > 0.9

    def test_pad_aware_scoring_reads_last_real_token(self, cfg):
        """ADVICE r3: with pad_token_id set, the reward head must score
        the last NON-pad position — a right-padded sequence and its
        unpadded prefix (scored at its true final token) agree exactly,
        and the score ignores how much padding follows."""
        from dlrover_tpu.rl.reward import RewardModel, reward_scores

        PAD = 0
        rm = RewardModel(cfg, seed=0, pad_token_id=PAD)
        body = np.array([[5, 7, 3, 9, 4, 6]], dtype=np.int32)
        padded_8 = np.pad(body, ((0, 0), (0, 2)), constant_values=PAD)
        padded_12 = np.pad(body, ((0, 0), (0, 6)), constant_values=PAD)
        s8, s12 = rm.score(padded_8), rm.score(padded_12)
        # causal model: positions 0..5 see identical context regardless
        # of trailing pads, so pad-aware scores match to fp tolerance
        np.testing.assert_allclose(s8, s12, rtol=1e-5)
        # and differ from the (wrong) final-position read
        naive = reward_scores(
            rm.params, jnp.asarray(padded_12), cfg, pad_token_id=None
        )
        assert abs(float(naive[0]) - float(s12[0])) > 1e-6

    def test_ppo_config_forwards_sampling_knobs(self, cfg):
        """ADVICE r3: PPOConfig.top_k/top_p must reach generate() in the
        rollout — with top_k=1 every rollout is greedy-deterministic."""
        engine = RLHFEngine(
            cfg,
            lambda tokens, p: np.zeros(len(tokens), dtype=np.float32),
            ppo=PPOConfig(
                rollout_batch=4, max_new_tokens=6, minibatch_size=4,
                ppo_epochs=1, top_k=1,
            ),
            seed=0,
        )
        prompts = np.tile(
            np.array([[2, 9, 4, 1]], dtype=np.int32), (4, 1)
        )
        exp = engine.make_experience(prompts)
        # identical prompts + top_k=1 => identical argmax completions
        assert (exp.tokens == exp.tokens[0]).all(), exp.tokens

    def test_restricted_sampling_keeps_ratio_centered(self, cfg):
        """The recorded old-policy logprobs must equal what the PPO
        update's scoring function produces for unchanged weights —
        under top_k/top_p/temperature restriction the SAMPLER's
        logprobs differ, and recording those would center the clip
        window off ratio=1 (code-review r4 finding)."""
        engine = RLHFEngine(
            cfg,
            lambda tokens, p: np.zeros(len(tokens), dtype=np.float32),
            ppo=PPOConfig(
                rollout_batch=4, max_new_tokens=6, minibatch_size=4,
                ppo_epochs=1, top_k=2, temperature=0.7,
            ),
            seed=0,
        )
        prompts = np.tile(
            np.array([[2, 9, 4, 1]], dtype=np.int32), (4, 1)
        )
        exp = engine.make_experience(prompts)
        rescored = sequence_logprobs(
            engine.actor_params, jnp.asarray(exp.tokens), cfg,
            prompt_len=4,
        )
        np.testing.assert_allclose(
            exp.logprobs, np.asarray(rescored), rtol=1e-5, atol=1e-6
        )

    # slow tier (budget): ~15s reward->PPO convergence A/B;
    # test_learns_preferences keeps the reward model's held-out
    # generalization in tier-1 and the seam is API-covered above
    @pytest.mark.slow
    def test_trained_reward_drives_ppo(self, cfg):
        """The trained reward model plugs into the PPO engine behind the
        same reward_fn seam, and PPO moves rollouts toward the preferred
        token distribution."""
        from dlrover_tpu.rl.reward import RewardModel

        rng = np.random.default_rng(1)
        chosen = rng.choice([3, 4], size=(64, 12), p=[0.9, 0.1]).astype(np.int32)
        rejected = rng.choice([11, 4], size=(64, 12), p=[0.9, 0.1]).astype(np.int32)
        rm = RewardModel(cfg, lr=1e-3, seed=0)
        for _ in range(30):
            rm.train_on_preferences(chosen, rejected)

        engine = RLHFEngine(
            cfg,
            rm.as_reward_fn(),
            ppo=PPOConfig(
                rollout_batch=16, max_new_tokens=8, minibatch_size=16,
                ppo_epochs=2, learning_rate=5e-3, kl_coef=0.01,
            ),
            seed=0,
        )
        prompts = np.zeros((16, 4), dtype=np.int32)
        before = float(rm.score(np.asarray(generate(
            engine.actor_params, jnp.asarray(prompts),
            jax.random.PRNGKey(9), cfg, max_new_tokens=8,
        )[0])).mean())
        for _ in range(6):
            engine.make_experience(prompts)
            engine.train(prompt_len=4)
        after = float(rm.score(np.asarray(generate(
            engine.actor_params, jnp.asarray(prompts),
            jax.random.PRNGKey(9), cfg, max_new_tokens=8,
        )[0])).mean())
        assert after > before, (before, after)


class TestHybridPlacement:
    @pytest.mark.slow  # ~19s: dual-mesh compile; budget-gated out of tier-1
    def test_train_and_rollout_use_different_shardings(self, cfg):
        """The weight-flow analog of the DS hybrid engine: actor weights
        train ZeRO-3-sharded (fsdp) and are explicitly resharded to the
        replicated rollout layout each generation phase; the cycle still
        learns and the two layouts are demonstrably different."""
        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

        train_mesh = build_mesh(MeshConfig(fsdp=4, dp=2))
        rollout_mesh = build_mesh(MeshConfig(dp=8))
        target = 7

        def reward_fn(tokens, prompt_len):
            return (tokens[:, prompt_len:] == target).mean(axis=1) * 4.0

        engine = RLHFEngine(
            cfg,
            reward_fn,
            ppo=PPOConfig(
                rollout_batch=16, max_new_tokens=8, minibatch_size=16,
                ppo_epochs=1, learning_rate=5e-3, kl_coef=0.01,
            ),
            seed=0,
            train_mesh=train_mesh,
            rollout_mesh=rollout_mesh,
        )
        # train layout: wq sharded over fsdp; ref (rollout) replicated
        wq = engine.actor_params["layers"][0]["attn"]["wq"]
        ref_wq = engine.ref_params["layers"][0]["attn"]["wq"]
        assert not wq.sharding.is_fully_replicated
        assert ref_wq.sharding.is_fully_replicated
        for _ in range(2):
            exp = engine.make_experience(np.zeros((16, 4), dtype=np.int32))
            metrics = engine.train(prompt_len=4)
        assert np.isfinite(metrics["loss"])
        # actor weights stayed in the TRAIN layout across the cycle
        wq2 = engine.actor_params["layers"][0]["attn"]["wq"]
        assert not wq2.sharding.is_fully_replicated


class TestShardedRollout:
    """VERDICT r3 missing#1: rollout generation under a mesh — the
    multi-device inference engine analog (ref model_engine.py +
    ds_hybrid_engine/hybrid_engine.py:378)."""

    def test_sharded_generation_matches_unsharded(self, cfg, params):
        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

        mesh = build_mesh(MeshConfig(dp=4, tp=2))
        prompts = jnp.asarray(
            np.tile(np.array([[3, 11, 5, 2]], np.int32), (8, 1))
        )
        ref_toks, ref_lp = generate(
            params, prompts, jax.random.PRNGKey(5), cfg,
            max_new_tokens=8, greedy=True,
        )
        sh_toks, sh_lp = generate(
            params, prompts, jax.random.PRNGKey(5), cfg,
            max_new_tokens=8, greedy=True, mesh=mesh,
        )
        # tp-sharded matmuls reassociate the reductions, but greedy
        # decode must pick identical tokens on a real logit gap
        np.testing.assert_array_equal(
            np.asarray(sh_toks), np.asarray(ref_toks)
        )
        np.testing.assert_allclose(
            np.asarray(sh_lp), np.asarray(ref_lp), rtol=1e-4, atol=1e-5
        )
        # and the actual sampled path stays finite + in-vocab
        s_toks, s_lp = generate(
            params, prompts, jax.random.PRNGKey(6), cfg,
            max_new_tokens=8, temperature=0.8, top_k=4, mesh=mesh,
        )
        assert np.isfinite(np.asarray(s_lp)).all()
        assert (np.asarray(s_toks) < cfg.vocab_size).all()

    def test_engine_rollout_runs_tp_sharded(self, cfg):
        """With a dp×tp rollout mesh the actor's rollout copy (and the
        frozen ref) are REALLY tp-sharded — a 7B-class actor no longer
        needs to fit one chip — and the PPO cycle still runs."""
        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

        engine = RLHFEngine(
            cfg,
            lambda tokens, p: np.zeros(len(tokens), dtype=np.float32),
            ppo=PPOConfig(
                rollout_batch=8, max_new_tokens=6, minibatch_size=8,
                ppo_epochs=1,
            ),
            seed=0,
            train_mesh=build_mesh(MeshConfig(fsdp=4, dp=2)),
            rollout_mesh=build_mesh(MeshConfig(dp=4, tp=2)),
        )
        ref_wq = engine.ref_params["layers"][0]["attn"]["wq"]
        assert not ref_wq.sharding.is_fully_replicated
        exp = engine.make_experience(np.zeros((8, 4), dtype=np.int32))
        metrics = engine.train(prompt_len=4)
        assert np.isfinite(metrics["loss"])
        assert np.isfinite(exp.logprobs).all()


class TestSamplingControls:
    def test_top_k_restricts_support(self, cfg, params):
        """With top_k=1 sampling degenerates to greedy regardless of
        key, and the returned logprob is ~0 (probability 1 on the
        restricted support)."""
        prompts = np.zeros((4, 4), dtype=np.int32)
        toks_a, lp_a = generate(
            params, jnp.asarray(prompts), jax.random.PRNGKey(0), cfg,
            max_new_tokens=6, top_k=1,
        )
        toks_b, _ = generate(
            params, jnp.asarray(prompts), jax.random.PRNGKey(123), cfg,
            max_new_tokens=6, top_k=1,
        )
        np.testing.assert_array_equal(np.asarray(toks_a), np.asarray(toks_b))
        greedy, _ = generate(
            params, jnp.asarray(prompts), jax.random.PRNGKey(0), cfg,
            max_new_tokens=6, greedy=True,
        )
        np.testing.assert_array_equal(np.asarray(toks_a), np.asarray(greedy))
        np.testing.assert_allclose(np.asarray(lp_a), 0.0, atol=1e-5)

    def test_top_p_masks_tail(self):
        """Nucleus masking keeps the smallest prefix reaching p and
        always at least the argmax."""
        from dlrover_tpu.rl.generation import _mask_logits

        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
        out = np.asarray(_mask_logits(logits, 0, 0.6))
        # 0.5 < 0.6 -> token 1 (cumulative-before 0.5) also kept;
        # cumulative-before for token 2 is 0.8 >= 0.6 -> masked
        assert np.isfinite(out[0, 0]) and np.isfinite(out[0, 1])
        assert out[0, 2] == -np.inf and out[0, 3] == -np.inf
        # extreme p keeps only the argmax
        out = np.asarray(_mask_logits(logits, 0, 1e-9))
        assert np.isfinite(out[0, 0]) and (out[0, 1:] == -np.inf).all()

    def test_top_k_clamps_and_composes_with_top_p(self):
        from dlrover_tpu.rl.generation import _mask_logits

        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
        # top_k beyond vocab: keep-all (no crash)
        out = np.asarray(_mask_logits(logits, 100, 1.0))
        assert np.isfinite(out).all()
        # top_k=2 then nucleus over the RENORMALIZED {0.625, 0.375}:
        # p=0.7 keeps token 0 (0 < 0.7) and token 1 (0.625 < 0.7)
        out = np.asarray(_mask_logits(logits, 2, 0.7))
        assert np.isfinite(out[0, :2]).all()
        assert (out[0, 2:] == -np.inf).all()
        # p=0.5 keeps only token 0 of the restricted support
        out = np.asarray(_mask_logits(logits, 2, 0.5))
        assert np.isfinite(out[0, 0]) and (out[0, 1:] == -np.inf).all()
