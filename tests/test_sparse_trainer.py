"""SparseTrainer: embedding-backed training with checkpoint + failover."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.embedding import ShardedKvEmbedding
from dlrover_tpu.trainer.sparse import SparseTrainer

DIM = 16


def _dense_step_factory(lr=0.3):
    @jax.jit
    def loss_fn(w, rows, y):
        p = jax.nn.sigmoid(rows @ w)
        return -jnp.mean(
            y * jnp.log(p + 1e-7) + (1 - y) * jnp.log(1 - p + 1e-7)
        )

    grad_fn = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1)))

    def dense_step(w, rows, batch):
        y = jnp.asarray(batch)
        loss, (gw, grows) = grad_fn(w, jnp.asarray(rows), y)
        return w - lr * gw, grows, {"loss": float(loss)}

    return dense_step


def _data(n=256, n_ids=40, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, n_ids, n)
    return ids, (ids % 2).astype(np.float32)


class TestSparseTrainer:
    def test_learns_parity(self):
        emb = ShardedKvEmbedding(2, DIM, seed=0)
        t = SparseTrainer(
            emb, jnp.zeros((DIM,)), _dense_step_factory(),
            sparse_optimizer="adagrad", sparse_lr=0.5,
        )
        ids, labels = _data()
        losses = [
            t.train_step(ids[:128], labels[:128]) ["loss"]
            for _ in range(25)
        ]
        assert losses[-1] < losses[0] * 0.6, losses[::8]

    @pytest.mark.parametrize(
        "opt",
        [
            "adam", "momentum", "group_ftrl", "group_adam", "lamb",
            "adabelief", "amsgrad",
        ],
    )
    def test_all_sparse_optimizers_run(self, opt):
        emb = ShardedKvEmbedding(2, DIM, seed=0, num_slots=3)
        t = SparseTrainer(
            emb, jnp.zeros((DIM,)), _dense_step_factory(),
            sparse_optimizer=opt, sparse_lr=0.05,
        )
        ids, labels = _data(64)
        m = t.train_step(ids[:32], labels[:32])
        assert np.isfinite(m["loss"])

    def test_checkpoint_restore_resumes(self, tmp_path):
        emb = ShardedKvEmbedding(2, DIM, seed=0)
        t1 = SparseTrainer(
            emb, jnp.zeros((DIM,)), _dense_step_factory(),
            ckpt_dir=str(tmp_path), sparse_lr=0.5,
        )
        ids, labels = _data()
        for _ in range(5):
            t1.train_step(ids[:64], labels[:64])
        t1.save_embedding()
        rows_before = emb.gather(ids[:10], insert_missing=False)

        emb2 = ShardedKvEmbedding(3, DIM, seed=999)  # different shards/seed
        t2 = SparseTrainer(
            emb2, jnp.zeros((DIM,)), _dense_step_factory(),
            ckpt_dir=str(tmp_path),
        )
        assert t2.restore_embedding()
        assert t2.step == 5
        np.testing.assert_array_equal(
            emb2.gather(ids[:10], insert_missing=False), rows_before
        )

    def test_failover_on_cluster_version_bump(self, tmp_path):
        class _FakeClient:
            def __init__(self):
                self.version = 0

            def get_cluster_version(self, version_type="global"):
                return self.version

        client = _FakeClient()
        emb = ShardedKvEmbedding(2, DIM, seed=0)
        t = SparseTrainer(
            emb, jnp.zeros((DIM,)), _dense_step_factory(),
            ckpt_dir=str(tmp_path), master_client=client, sparse_lr=0.5,
        )
        ids, labels = _data()
        for _ in range(3):
            t.train_step(ids[:64], labels[:64])
        t.save_embedding()
        saved = emb.gather(ids[:10], insert_missing=False)
        assert not t.check_failover()  # version unchanged

        # more training moves the rows past the snapshot; then a reshard
        # elsewhere bumps the version -> trainer must reload the snapshot
        for _ in range(3):
            t.train_step(ids[:64], labels[:64])
        client.version = 1
        assert t.check_failover()
        assert t.step == 3
        np.testing.assert_array_equal(
            emb.gather(ids[:10], insert_missing=False), saved
        )


def _device_trainer(ckpt_dir="", capacity=128, lr=0.5, client=None, **kw):
    from dlrover_tpu.ops.embedding.device_tier import DeviceSparseEmbedding

    host = ShardedKvEmbedding(2, DIM, num_slots=1, seed=0)
    emb = DeviceSparseEmbedding(
        host, capacity=capacity, sparse_optimizer="adagrad", lr=lr
    )
    t = SparseTrainer(
        emb, jnp.zeros((DIM,)), _dense_step_factory(),
        ckpt_dir=str(ckpt_dir), master_client=client, **kw,
    )
    return t, host, emb


def _stream(n, bs=64, vocab=40, seed=7):
    for s in range(n):
        r = np.random.default_rng(seed * 1000 + s)
        ids = r.integers(0, vocab, bs).astype(np.int64)
        yield ids, (ids % 2).astype(np.float32)


class TestDeviceModeTrainer:
    def test_device_cycle_learns_parity(self):
        t, _, emb = _device_trainer()
        losses = [
            m["loss"] for m in t.run(_stream(25), overlapped=True)
        ]
        assert losses[-1] < losses[0] * 0.6, losses[::8]
        emb.close()

    def test_sync_and_overlapped_device_runs_are_bitwise(self):
        """The pipeline only changes WHEN rows are faulted in, never
        the math: the overlapped run must reproduce the inline run's
        losses bitwise."""
        ta, _, ea = _device_trainer()
        la = [m["loss"] for m in ta.run(_stream(12), overlapped=False)]
        ea.close()
        tb, _, eb = _device_trainer()
        lb = [m["loss"] for m in tb.run(_stream(12), overlapped=True)]
        eb.close()
        assert la == lb

    def test_chunked_delta_resume_is_bitwise(self, tmp_path):
        from dlrover_tpu.ops.embedding import IncrementalCheckpointManager

        ta, ha, ea = _device_trainer()
        mgr = IncrementalCheckpointManager(ha, str(tmp_path), full_every=4)
        ta.run(_stream(3), overlapped=False)
        ea.flush()
        mgr.save(step=3)  # full
        ta.run((x for i, x in enumerate(_stream(5)) if i >= 3),
               overlapped=False)
        ea.flush()
        stager = mgr.begin_chunked_save(step=5, chunk_bytes=4 << 10)
        dense_at_5 = np.asarray(ta.dense_params)
        tail_a = []
        for i, (ids, y) in enumerate(_stream(9)):
            if i < 5:
                continue
            stager.advance(budget_s=0.001)
            tail_a.append(ta.train_step_device(ids, y)["loss"])
        stager.commit()
        ea.close()

        tb, hb, eb = _device_trainer()
        mgr_b = IncrementalCheckpointManager(hb, str(tmp_path))
        assert mgr_b.restore() == 5
        tb.step = 5
        tb.dense_params = jnp.asarray(dense_at_5)
        tail_b = []
        for i, (ids, y) in enumerate(_stream(9)):
            if i < 5:
                continue
            tail_b.append(tb.train_step_device(ids, y)["loss"])
        eb.close()
        assert tail_a == tail_b  # bitwise loss continuity

    def test_telemetry_rides_train_metrics_report(self):
        class _Client:
            def __init__(self):
                self.reports = []

            def get_cluster_version(self, version_type="global"):
                return 0

            def report_train_metrics(self, step, metrics):
                self.reports.append((step, metrics))

        c = _Client()
        t, _, emb = _device_trainer(client=c)
        t.run(_stream(3), overlapped=False)
        scalars = t.report_telemetry()
        assert scalars["sparse_step"] == 3.0
        assert "emb_gather_hit_pct" in scalars
        assert c.reports and c.reports[-1][0] == 3
        assert "emb_host_leg_ms" in c.reports[-1][1]
        emb.close()


class TestFailoverHardening:
    class _Client:
        def __init__(self):
            self.version = 0
            self.fail = False

        def get_cluster_version(self, version_type="global"):
            if self.fail:
                raise ConnectionError("master unreachable")
            return self.version

    def test_poll_failure_degrades_to_no_change(self, tmp_path):
        c = self._Client()
        t, _, emb = _device_trainer(ckpt_dir=tmp_path, client=c)
        c.fail = True
        assert t.check_failover() is False  # no crash, no refresh
        c.fail = False
        assert t.check_failover() is False  # version unchanged
        emb.close()

    def test_poll_failure_at_init_raises(self):
        c = self._Client()
        c.fail = True
        with pytest.raises(ConnectionError):
            _device_trainer(client=c)

    def test_version_bump_warm_reshards_and_books_ledger(self, tmp_path):
        from dlrover_tpu.obs.goodput import (
            GoodputLedger,
            install_default_ledger,
        )

        ledger = install_default_ledger(GoodputLedger())
        try:
            c = self._Client()
            t, host, emb = _device_trainer(
                ckpt_dir=tmp_path, client=c,
                target_shards_fn=lambda: 3,
            )
            t.run(_stream(3), overlapped=False)
            c.version = 1
            assert t.check_failover() is True
            assert host.num_shards == 3
            rep = ledger.snapshot()
            assert rep.seconds["restart_replay"] > 0
            emb.close()
        finally:
            install_default_ledger(GoodputLedger())

    def test_version_bump_reimports_and_books_ledger(self, tmp_path):
        from dlrover_tpu.obs.goodput import (
            GoodputLedger,
            install_default_ledger,
        )

        ledger = install_default_ledger(GoodputLedger())
        try:
            c = self._Client()
            t, host, emb = _device_trainer(ckpt_dir=tmp_path, client=c)
            t.run(_stream(4), overlapped=False)
            t.save_embedding()
            saved = np.asarray(emb.gather(np.arange(10))).copy()
            t.run((x for i, x in enumerate(_stream(6)) if i >= 4),
                  overlapped=False)
            c.version = 1
            assert t.check_failover() is True  # no target: re-import
            np.testing.assert_array_equal(
                np.asarray(emb.gather(np.arange(10))), saved
            )
            assert ledger.snapshot().seconds["restart_replay"] > 0
            emb.close()
        finally:
            install_default_ledger(GoodputLedger())


class TestCheckpointIntegrity:
    def test_corrupt_newest_rolls_back_to_previous(self, tmp_path):
        import os

        t, _, emb = _device_trainer(ckpt_dir=tmp_path)
        t.run(_stream(5), overlapped=False)
        t.save_embedding()
        vals = np.asarray(emb.gather(np.arange(10))).copy()
        dense5 = np.asarray(t.dense_params).copy()
        t.run((x for i, x in enumerate(_stream(8)) if i >= 5),
              overlapped=False)
        t.save_embedding()  # rotates the first save to .prev
        p = str(tmp_path / "embedding_state.npz")
        blob = open(p, "rb").read()
        open(p, "wb").write(blob[: len(blob) // 2])  # torn write

        t2, _, emb2 = _device_trainer(ckpt_dir=tmp_path)
        assert t2.restore_embedding()
        assert t2.step == 5  # the previous good save
        np.testing.assert_array_equal(
            np.asarray(emb2.gather(np.arange(10))), vals
        )
        np.testing.assert_array_equal(np.asarray(t2.dense_params), dense5)
        assert os.path.exists(p + ".corrupt")
        emb.close()
        emb2.close()

    def test_both_corrupt_restores_nothing(self, tmp_path):
        t, _, emb = _device_trainer(ckpt_dir=tmp_path)
        t.run(_stream(2), overlapped=False)
        t.save_embedding()
        t.save_embedding()
        for name in ("embedding_state.npz", "embedding_state.prev.npz"):
            p = str(tmp_path / name)
            open(p, "wb").write(b"garbage")
        t2, _, emb2 = _device_trainer(ckpt_dir=tmp_path)
        assert t2.restore_embedding() is False
        emb.close()
        emb2.close()

    @pytest.mark.parametrize("kind", ["torn_write", "bit_flip"])
    def test_export_fault_detected_and_rolled_back(self, tmp_path, kind):
        """Chaos matrix for the embedding.export site: a corrupted
        export must be detected at restore and roll back to the
        previous good file — never restore silently."""
        from dlrover_tpu.common import faults

        t, _, emb = _device_trainer(ckpt_dir=tmp_path)
        t.run(_stream(4), overlapped=False)
        t.save_embedding()  # good
        vals = np.asarray(emb.gather(np.arange(10))).copy()
        faults.reset()
        try:
            faults.configure(f"embedding.export:{kind}:1.0:3")
            t.run((x for i, x in enumerate(_stream(6)) if i >= 4),
                  overlapped=False)
            t.save_embedding()  # corrupted in flight
            assert faults.triggered_total() > 0
        finally:
            faults.reset()
        t2, _, emb2 = _device_trainer(ckpt_dir=tmp_path)
        assert t2.restore_embedding()
        assert t2.step == 4  # rolled back
        np.testing.assert_array_equal(
            np.asarray(emb2.gather(np.arange(10))), vals
        )
        emb.close()
        emb2.close()

    def test_import_fault_site_fires(self, tmp_path):
        from dlrover_tpu.common import faults

        t, _, emb = _device_trainer(ckpt_dir=tmp_path)
        t.run(_stream(2), overlapped=False)
        t.save_embedding()
        faults.reset()
        try:
            faults.configure("embedding.import:io_error:1.0")
            t2, _, emb2 = _device_trainer(ckpt_dir=tmp_path)
            with pytest.raises(OSError):
                t2.restore_embedding()
            emb2.close()
        finally:
            faults.reset()
        emb.close()
