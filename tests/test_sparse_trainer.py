"""SparseTrainer: embedding-backed training with checkpoint + failover."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.embedding import ShardedKvEmbedding
from dlrover_tpu.trainer.sparse import SparseTrainer

DIM = 16


def _dense_step_factory(lr=0.3):
    @jax.jit
    def loss_fn(w, rows, y):
        p = jax.nn.sigmoid(rows @ w)
        return -jnp.mean(
            y * jnp.log(p + 1e-7) + (1 - y) * jnp.log(1 - p + 1e-7)
        )

    grad_fn = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1)))

    def dense_step(w, rows, batch):
        y = jnp.asarray(batch)
        loss, (gw, grows) = grad_fn(w, jnp.asarray(rows), y)
        return w - lr * gw, grows, {"loss": float(loss)}

    return dense_step


def _data(n=256, n_ids=40, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, n_ids, n)
    return ids, (ids % 2).astype(np.float32)


class TestSparseTrainer:
    def test_learns_parity(self):
        emb = ShardedKvEmbedding(2, DIM, seed=0)
        t = SparseTrainer(
            emb, jnp.zeros((DIM,)), _dense_step_factory(),
            sparse_optimizer="adagrad", sparse_lr=0.5,
        )
        ids, labels = _data()
        losses = [
            t.train_step(ids[:128], labels[:128]) ["loss"]
            for _ in range(25)
        ]
        assert losses[-1] < losses[0] * 0.6, losses[::8]

    @pytest.mark.parametrize(
        "opt",
        [
            "adam", "momentum", "group_ftrl", "group_adam", "lamb",
            "adabelief", "amsgrad",
        ],
    )
    def test_all_sparse_optimizers_run(self, opt):
        emb = ShardedKvEmbedding(2, DIM, seed=0, num_slots=3)
        t = SparseTrainer(
            emb, jnp.zeros((DIM,)), _dense_step_factory(),
            sparse_optimizer=opt, sparse_lr=0.05,
        )
        ids, labels = _data(64)
        m = t.train_step(ids[:32], labels[:32])
        assert np.isfinite(m["loss"])

    def test_checkpoint_restore_resumes(self, tmp_path):
        emb = ShardedKvEmbedding(2, DIM, seed=0)
        t1 = SparseTrainer(
            emb, jnp.zeros((DIM,)), _dense_step_factory(),
            ckpt_dir=str(tmp_path), sparse_lr=0.5,
        )
        ids, labels = _data()
        for _ in range(5):
            t1.train_step(ids[:64], labels[:64])
        t1.save_embedding()
        rows_before = emb.gather(ids[:10], insert_missing=False)

        emb2 = ShardedKvEmbedding(3, DIM, seed=999)  # different shards/seed
        t2 = SparseTrainer(
            emb2, jnp.zeros((DIM,)), _dense_step_factory(),
            ckpt_dir=str(tmp_path),
        )
        assert t2.restore_embedding()
        assert t2.step == 5
        np.testing.assert_array_equal(
            emb2.gather(ids[:10], insert_missing=False), rows_before
        )

    def test_failover_on_cluster_version_bump(self, tmp_path):
        class _FakeClient:
            def __init__(self):
                self.version = 0

            def get_cluster_version(self, version_type="global"):
                return self.version

        client = _FakeClient()
        emb = ShardedKvEmbedding(2, DIM, seed=0)
        t = SparseTrainer(
            emb, jnp.zeros((DIM,)), _dense_step_factory(),
            ckpt_dir=str(tmp_path), master_client=client, sparse_lr=0.5,
        )
        ids, labels = _data()
        for _ in range(3):
            t.train_step(ids[:64], labels[:64])
        t.save_embedding()
        saved = emb.gather(ids[:10], insert_missing=False)
        assert not t.check_failover()  # version unchanged

        # more training moves the rows past the snapshot; then a reshard
        # elsewhere bumps the version -> trainer must reload the snapshot
        for _ in range(3):
            t.train_step(ids[:64], labels[:64])
        client.version = 1
        assert t.check_failover()
        assert t.step == 3
        np.testing.assert_array_equal(
            emb.gather(ids[:10], insert_missing=False), saved
        )
