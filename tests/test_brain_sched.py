"""Brain cluster scheduler: curve fitting, allocation, the plan table's
redeliver/ack/expire accounting, the master-side executor, the unified
algorithm verdicts, BrainClient retry treatment, and brain_ctl."""

import time

import pytest

from dlrover_tpu.brain.plan_exec import PlanExecutor
from dlrover_tpu.brain.scheduler import (
    DEFAULT_EXPONENT,
    ClusterScheduler,
    JobState,
    ScalingCurve,
    fit_scaling_curve,
    plan_signature,
    solve_allocation,
)
from dlrover_tpu.brain.service import (
    BrainClient,
    BrainServicer,
    start_brain_service,
)
from dlrover_tpu.common import comm


def _sample(nodes, sps, goodput=0.0, ts=None):
    return comm.JobMetricsSample(
        timestamp=time.time() if ts is None else ts,
        alive_nodes=nodes,
        steps_per_sec=sps,
        goodput_pct=goodput,
    )


def _feed(servicer, job, sizes_speeds, goodput=99.0, ts=None):
    base = time.time() if ts is None else ts
    for i, (n, sps) in enumerate(sizes_speeds):
        servicer.persist_metrics(
            job, _sample(n, sps, goodput=goodput, ts=base + i * 0.001)
        )


def _scheduler(servicer, **kw):
    kw.setdefault("total_chips", 12)
    kw.setdefault("min_dwell_s", 0.0)
    kw.setdefault("hysteresis_frac", 0.0)
    return ClusterScheduler(servicer, **kw)


class TestScalingCurve:
    def test_power_law_fit_recovers_exponent(self):
        true = lambda n: 3.0 * n**0.8  # noqa: E731
        c = fit_scaling_curve({n: true(n) for n in (2, 4, 8, 16)})
        assert abs(c.b - 0.8) < 1e-6
        assert abs(c.a - 3.0) < 1e-6
        assert abs(c.predict(32) - true(32)) < 1e-3

    def test_single_point_uses_default_exponent(self):
        c = fit_scaling_curve({4: 20.0})
        assert c.b == DEFAULT_EXPONENT
        assert abs(c.predict(4) - 20.0) < 1e-9

    def test_exponent_clamped_to_concave(self):
        # superlinear observations (cache effects, noise) must not
        # produce a convex curve that breaks greedy optimality
        c = fit_scaling_curve({2: 10.0, 4: 50.0})
        assert c.b == 1.0
        # and "more chips slower" noise must not go negative
        c2 = fit_scaling_curve({2: 10.0, 4: 5.0})
        assert c2.b == 0.0

    def test_empty_and_junk_points(self):
        assert fit_scaling_curve({}) is None
        assert fit_scaling_curve({0: 5.0, 3: 0.0}) is None


class TestSolveAllocation:
    def _job(self, name, b, current=4, **kw):
        return JobState(
            job=name,
            curve=ScalingCurve(a=10.0, b=b),
            current=current,
            **kw,
        )

    def test_linear_job_wins_chips_over_flat(self):
        jobs = [self._job("lin", 0.95), self._job("flat", 0.2)]
        alloc = solve_allocation(jobs, total_chips=8, node_unit=1)
        assert alloc["lin"] > alloc["flat"]
        assert sum(alloc.values()) <= 8
        assert alloc["flat"] >= 1  # starvation floor

    def test_respects_node_unit(self):
        jobs = [self._job("a", 0.9), self._job("b", 0.5)]
        alloc = solve_allocation(jobs, total_chips=16, node_unit=4)
        assert all(n % 4 == 0 for n in alloc.values())
        assert sum(alloc.values()) <= 16

    def test_frozen_job_is_pinned(self):
        jobs = [
            self._job("lin", 0.95),
            self._job("flat", 0.2, current=6, frozen=True),
        ]
        alloc = solve_allocation(jobs, total_chips=12, node_unit=1)
        assert alloc["flat"] == 6  # dwell pin holds its chips
        assert alloc["lin"] <= 6

    def test_flat_curves_leave_chips_idle(self):
        # zero-exponent curves: n^0 is constant, marginal gain 0 —
        # chips must not be burned on jobs they cannot speed up
        jobs = [self._job("a", 0.0), self._job("b", 0.0)]
        alloc = solve_allocation(jobs, total_chips=100, node_unit=1)
        assert sum(alloc.values()) == 2  # floors only

    def test_goodput_weighting_shifts_chips(self):
        # identical curves, one job at half goodput: its chips yield
        # half the productive throughput -> the healthy job wins ties
        sick = self._job("sick", 0.7, goodput_pct=40.0)
        well = self._job("well", 0.7, goodput_pct=95.0)
        alloc = solve_allocation([sick, well], 9, node_unit=1)
        assert alloc["well"] > alloc["sick"]

    def test_oversubscribed_keeps_current(self):
        jobs = [
            self._job("a", 0.9, current=8, frozen=True),
            self._job("b", 0.9, current=8, frozen=True),
        ]
        alloc = solve_allocation(jobs, total_chips=4, node_unit=1)
        assert alloc == {"a": 8, "b": 8}


class TestPlanTable:
    def test_emit_poll_ack_lifecycle(self):
        s = BrainServicer()
        try:
            v = s.next_plan_version()
            s.record_cluster_plan(
                v,
                [{"job": "j1", "worker_count": 6, "prev_count": 4}],
                time.time(),
            )
            sl = s.cluster_plan_slice("j1")
            assert sl is not None and sl.worker_count == 6
            assert sl.sig == plan_signature(v, "j1", 6, sl.issued_ts)
            # an unacked poll redelivers the same slice
            again = s.cluster_plan_slice("j1")
            assert again is not None and again.version == v
            # the ack clears it
            assert s.cluster_plan_slice("j1", ack_version=v) is None
            assert s.plan_status_counts() == {"acked": 1}
            assert s.last_planned_count("j1") == 6
        finally:
            s.close()

    def test_outcome_report_is_the_sign_off(self):
        s = BrainServicer()
        try:
            v = s.next_plan_version()
            s.record_cluster_plan(
                v, [{"job": "j1", "worker_count": 2}], time.time()
            )
            s.record_plan_outcome(
                comm.PlanOutcomeReport(
                    job_name="j1",
                    version=v,
                    worker_count=2,
                    decision_to_resized_ms=42.0,
                    realized_goodput_pct=97.5,
                )
            )
            assert s.plan_status_counts() == {"acked": 1}
            assert s.latest_outcome_latencies() == {"j1": 42.0}
            hist = s.plan_history("j1")
            assert hist[0]["realized_goodput_pct"] == 97.5
            # replay (the retried idempotent report) is a no-op
            s.record_plan_outcome(
                comm.PlanOutcomeReport(
                    job_name="j1", version=v, worker_count=2,
                    decision_to_resized_ms=42.0,
                )
            )
            assert len(s.plan_history("j1")) == 1
        finally:
            s.close()

    def test_new_version_supersedes_pending(self):
        s = BrainServicer()
        try:
            s.record_cluster_plan(
                1, [{"job": "j1", "worker_count": 2}], time.time()
            )
            s.record_cluster_plan(
                2, [{"job": "j1", "worker_count": 8}], time.time()
            )
            sl = s.cluster_plan_slice("j1")
            assert sl.version == 2 and sl.worker_count == 8
            assert s.plan_status_counts() == {
                "pending": 1,
                "superseded": 1,
            }
        finally:
            s.close()

    def test_unacked_plans_expire_not_vanish(self):
        s = BrainServicer()
        try:
            s.record_cluster_plan(
                1, [{"job": "dead", "worker_count": 2}], time.time() - 100
            )
            assert s.expire_stale_plans(time.time() - 50) == 1
            assert s.plan_status_counts() == {"expired": 1}
            assert s.cluster_plan_slice("dead") is None
            # an expired plan is NOT the current allocation
            assert s.last_planned_count("dead") == 0
        finally:
            s.close()

    def test_active_jobs_windows_and_job_end(self):
        s = BrainServicer()
        try:
            now = time.time()
            _feed(s, "live", [(2, 5.0)], ts=now)
            _feed(s, "stale", [(2, 5.0)], ts=now - 1000)
            _feed(s, "done", [(2, 5.0)], ts=now)
            s.record_job_end(
                comm.BrainJobEndReport(job_name="done")
            )
            assert s.active_jobs(now - 300) == ["live"]
            # a resubmitted job (fresh rows after its end) is active
            _feed(s, "done", [(2, 6.0)], ts=now + 10)
            assert s.active_jobs(now - 300) == ["done", "live"]
        finally:
            s.close()


class TestSchedulerPass:
    def test_pass_reallocates_toward_better_scaler(self):
        s = BrainServicer()
        try:
            sched = _scheduler(s, total_chips=8)
            _feed(s, "lin", [(4, 10 * 4**0.95)])
            _feed(s, "flat", [(4, 10 * 4**0.2)])
            v = sched.run_pass()
            assert v is not None
            lin = s.cluster_plan_slice("lin")
            flat = s.cluster_plan_slice("flat")
            assert lin is not None and lin.worker_count > 4
            assert flat is not None and flat.worker_count < 4
            assert flat.worker_count >= 1  # starvation floor
        finally:
            s.close()

    def test_hysteresis_holds_marginal_gains(self):
        s = BrainServicer()
        try:
            # identical jobs at the optimum: any move is churn
            sched = _scheduler(s, total_chips=8, hysteresis_frac=0.05)
            _feed(s, "a", [(4, 20.0)])
            _feed(s, "b", [(4, 20.0)])
            assert sched.run_pass() is None
            assert s.plan_status_counts() == {}
        finally:
            s.close()

    def test_min_dwell_pins_recently_resized(self):
        s = BrainServicer()
        try:
            sched = _scheduler(s, total_chips=8, min_dwell_s=3600.0)
            _feed(s, "lin", [(4, 10 * 4**0.95)])
            _feed(s, "flat", [(4, 10 * 4**0.2)])
            v1 = sched.run_pass()
            assert v1 is not None
            # both jobs just changed: the very next pass pins them
            assert sched.run_pass() is None
        finally:
            s.close()

    def test_goodput_rows_drive_the_objective(self):
        """The PR-7 goodput_pct column (the fleet_goodput number the
        collector persists) is consumed as the utility weight — same
        curves, the low-goodput job loses chips."""
        s = BrainServicer()
        try:
            sched = _scheduler(s, total_chips=9)
            _feed(s, "sick", [(4, 20.0)], goodput=40.0)
            _feed(s, "well", [(4, 20.0)], goodput=95.0)
            assert sched.run_pass() is not None
            well = s.cluster_plan_slice("well")
            sick = s.cluster_plan_slice("sick")
            got = {
                "well": well.worker_count if well else 4,
                "sick": sick.worker_count if sick else 4,
            }
            assert got["well"] > got["sick"]
        finally:
            s.close()

    def test_feedback_row_closes_the_loop(self):
        """The scheduler's next pass sees the outcome of its last one:
        the acked plan's count becomes the job's current allocation."""
        s = BrainServicer()
        try:
            sched = _scheduler(s, total_chips=8)
            _feed(s, "lin", [(4, 10 * 4**0.95)])
            _feed(s, "flat", [(4, 10 * 4**0.2)])
            v = sched.run_pass()
            lin = s.cluster_plan_slice("lin")
            s.record_plan_outcome(
                comm.PlanOutcomeReport(
                    job_name="lin", version=v,
                    worker_count=lin.worker_count,
                    decision_to_resized_ms=9.0,
                )
            )
            st = sched.job_state("lin", time.time())
            assert st.current == lin.worker_count
        finally:
            s.close()

    def test_underperformance_verdict_lands_in_node_events(self):
        """Satellite: run_algorithms verdicts feed the scheduler pass
        and are persisted as node_events rows, once per episode."""
        s = BrainServicer()
        try:
            # fleet history: someone completed at 4 nodes, 20 steps/s
            _feed(s, "hist", [(4, 20.0)])
            s.record_job_end(
                comm.BrainJobEndReport(
                    job_name="hist", exit_reason="completed"
                )
            )
            sched = _scheduler(s, total_chips=8)
            _feed(s, "slow", [(4, 5.0)])  # 25% of fleet best
            sched.run_pass()
            events = s.node_events(job="slow", event="underperformance")
            assert len(events) == 1
            sched.run_pass()  # same episode: no re-fire
            assert (
                len(s.node_events(job="slow", event="underperformance"))
                == 1
            )
        finally:
            s.close()

    def test_hot_verdict_raises_floor(self):
        s = BrainServicer()
        try:
            sched = _scheduler(s, total_chips=8)
            _feed(s, "hot", [(2, 10.0)] * 6)
            for nid, host in ((0, "h0"), (1, "h1")):
                s.record_node_event(
                    comm.BrainNodeEventReport(
                        job_name="hot", node_id=nid, hostname=host,
                        event="hot", cpu_percent=96.0,
                    )
                )
            st = sched.job_state("hot", time.time())
            assert "hot" in st.verdicts
            assert st.floor >= 3  # current 2 + one unit
        finally:
            s.close()

    def test_bad_node_exclusion_rides_the_slice(self):
        s = BrainServicer()
        try:
            for job in ("j1", "j2"):
                s.record_node_event(
                    comm.BrainNodeEventReport(
                        job_name=job, node_id=0, hostname="cursed",
                        event="failed",
                    )
                )
            sched = _scheduler(s, total_chips=8)
            _feed(s, "lin", [(4, 10 * 4**0.95)])
            _feed(s, "flat", [(4, 10 * 4**0.2)])
            assert sched.run_pass() is not None
            sl = s.cluster_plan_slice("lin")
            assert sl.exclude_hosts == ["cursed"]
        finally:
            s.close()

    def test_gauges_exported(self):
        from dlrover_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        s = BrainServicer()
        try:
            sched = _scheduler(s, total_chips=8, registry=reg)
            _feed(s, "lin", [(4, 10 * 4**0.95)])
            _feed(s, "flat", [(4, 10 * 4**0.2)])
            sched.run_pass()
            text = reg.prometheus_text()
            assert 'dlrover_brain_allocation{job="lin"}' in text
            assert "dlrover_brain_plan_version 1" in text
            assert 'dlrover_brain_plans{status="pending"} 2' in text
            assert "dlrover_brain_plans_emitted 2" in text
        finally:
            s.close()

    def test_scheduler_survives_brain_restart(self, tmp_path):
        """Dwell bookkeeping and plan versions are seeded from the
        store: a restarted Brain neither replays version 1 nor
        immediately re-resizes a job inside its dwell window."""
        db = str(tmp_path / "brain.db")
        s = BrainServicer(db_path=db)
        sched = _scheduler(s, total_chips=8)
        _feed(s, "lin", [(4, 10 * 4**0.95)])
        _feed(s, "flat", [(4, 10 * 4**0.2)])
        v1 = sched.run_pass()
        assert v1 == 1
        s.close()

        s2 = BrainServicer(db_path=db)
        try:
            sched2 = _scheduler(s2, total_chips=8, min_dwell_s=3600.0)
            assert s2.next_plan_version() == 2
            # both jobs changed moments ago: dwell pins them
            _feed(s2, "lin", [(4, 10 * 4**0.95)])
            assert sched2.run_pass() is None
        finally:
            s2.close()


class _Exec:
    """One simulated job master: auto-scaler on the local backend."""

    def __init__(self, addr, job, start_n=4, goodput_fn=None):
        from dlrover_tpu.master.job_auto_scaler import JobAutoScaler
        from dlrover_tpu.master.job_manager import JobManager
        from dlrover_tpu.master.scaler import CallbackScaler

        self.jm = JobManager()
        self.jm.create_initial_nodes(start_n)
        self.scaler = CallbackScaler(lambda plan: None)
        self.auto = JobAutoScaler(
            self.jm, scaler=self.scaler, target_nodes=start_n
        )
        self.client = BrainClient(addr, job)
        self.executor = PlanExecutor(
            self.client, self.auto, goodput_fn=goodput_fn
        )

    def close(self):
        self.client.close()


@pytest.fixture()
def brain_sched():
    server, servicer, addr = start_brain_service(
        scheduler=True, total_chips=8
    )
    servicer.scheduler.stop()  # tests drive passes manually
    servicer.scheduler.min_dwell_s = 0.0
    servicer.scheduler.hysteresis_frac = 0.0
    yield servicer, addr
    server.stop(grace=1)
    servicer.close()


class TestPlanExecutor:
    def test_closed_loop_over_grpc(self, brain_sched):
        servicer, addr = brain_sched
        lin = _Exec(addr, "lin", goodput_fn=lambda: 88.0)
        flat = _Exec(addr, "flat")
        try:
            lin.client.persist_metrics(_sample(4, 10 * 4**0.95))
            flat.client.persist_metrics(_sample(4, 10 * 4**0.2))
            v = servicer.scheduler.run_pass()
            assert v is not None
            assert lin.executor.poll_once() == v
            assert flat.executor.poll_once() == v
            assert lin.auto.target > 4 > flat.auto.target
            # outcome feedback landed, with the goodput the master saw
            hist = servicer.plan_history("lin")
            assert hist[0]["status"] == "acked"
            assert hist[0]["decision_to_resized_ms"] is not None
            assert hist[0]["realized_goodput_pct"] == 88.0
            # nothing pending -> the next poll is a no-op
            assert lin.executor.poll_once() is None
        finally:
            lin.close()
            flat.close()

    def test_redelivers_until_acked(self, brain_sched):
        """A lost outcome report leaves ack unadvanced: the slice is
        redelivered and re-executing scale_to is idempotent."""
        servicer, addr = brain_sched
        ex = _Exec(addr, "lin")
        try:
            ex.client.persist_metrics(_sample(4, 10 * 4**0.95))
            v = servicer.scheduler.run_pass()
            orig = ex.client.report_plan_outcome
            ex.client.report_plan_outcome = lambda *a, **k: (
                (_ for _ in ()).throw(ConnectionError("brain down"))
            )
            assert ex.executor.poll_once() == v
            assert ex.executor.acked_version == 0  # NOT acked
            assert servicer.plan_status_counts().get("pending") == 1
            ex.client.report_plan_outcome = orig
            assert ex.executor.poll_once() == v  # redelivered
            assert ex.executor.acked_version == v
            assert servicer.plan_status_counts() == {"acked": 1}
            assert len(ex.executor.executed) == 2
            assert ex.executor.executed[0][1] == ex.executor.executed[1][1]
        finally:
            ex.close()

    def test_bad_signature_rejected_not_executed(self, brain_sched):
        servicer, addr = brain_sched
        ex = _Exec(addr, "lin")
        try:
            ex.client.persist_metrics(_sample(4, 10 * 4**0.95))
            v = servicer.scheduler.run_pass()
            with servicer._lock:
                servicer._conn.execute(
                    "UPDATE cluster_plans SET worker_count = 999 "
                    "WHERE job='lin'"
                )
                servicer._conn.commit()
            assert ex.executor.poll_once() is None
            assert ex.auto.target == 4  # tampered plan not executed
            assert ex.executor.acked_version == v  # but not poison-looped
        finally:
            ex.close()

    def test_nonpositive_count_rejected(self, brain_sched):
        """The signature proves integrity, not sanity: a signed slice
        asking for <= 0 workers must be refused (eviction is the
        operator's call), not executed or redelivery-looped."""
        servicer, addr = brain_sched
        ex = _Exec(addr, "lin")
        try:
            servicer.record_cluster_plan(
                1,
                [{"job": "lin", "worker_count": 0, "prev_count": 4}],
                time.time(),
            )
            assert ex.executor.poll_once() is None
            assert ex.auto.target == 4
            assert ex.executor.acked_version == 1  # no poison loop
        finally:
            ex.close()

    def test_exclude_hosts_reach_the_scaler(self, brain_sched):
        servicer, addr = brain_sched
        seen = []

        class _Scaler:
            def scale(self, plan):
                pass

            def set_exclude_hosts(self, hosts):
                seen.append(tuple(hosts))

        ex = _Exec(addr, "lin")
        ex.auto._scaler = _Scaler()
        try:
            for job in ("j1", "j2"):
                servicer.record_node_event(
                    comm.BrainNodeEventReport(
                        job_name=job, hostname="cursed", event="oom"
                    )
                )
            ex.client.persist_metrics(_sample(4, 10 * 4**0.95))
            servicer.scheduler.run_pass()
            ex.executor.poll_once()
            assert ("cursed",) in seen
        finally:
            ex.close()


def test_master_env_wiring_runs_the_execution_leg(monkeypatch):
    """DLROVER_TPU_BRAIN_ADDR + a platform scaler wires the whole
    execution leg into LocalJobMaster with zero explicit plumbing: the
    PlanExecutor polls the job's slice and drives scale_to."""
    from dlrover_tpu.master.local_master import LocalJobMaster
    from dlrover_tpu.master.scaler import CallbackScaler

    server, servicer, addr = start_brain_service(
        scheduler=True, total_chips=8
    )
    servicer.scheduler.stop()
    servicer.scheduler.min_dwell_s = 0.0
    servicer.scheduler.hysteresis_frac = 0.0
    monkeypatch.setenv("DLROVER_TPU_BRAIN_ADDR", addr)
    monkeypatch.setenv("DLROVER_TPU_JOB_NAME", "env-exec")
    m = LocalJobMaster(
        node_num=4, scaler=CallbackScaler(lambda plan: None)
    )
    m.prepare()
    try:
        assert m.plan_executor is not None
        _feed(servicer, "env-exec", [(4, 10 * 4**0.95)])
        _feed(servicer, "env-other", [(4, 10 * 4**0.2)])
        v = servicer.scheduler.run_pass()
        assert v is not None
        # the daemon is running on its own cadence; drive one poll
        # deterministically instead of sleeping through an interval
        assert m.plan_executor.poll_once() in (v, None)
        assert m.auto_scaler.target > 4
        assert servicer.plan_history("env-exec")[0]["status"] == "acked"
    finally:
        m.stop()
        server.stop(grace=1)
        servicer.close()


class TestBrainClientRetries:
    """Satellite: the PR-5 retry treatment on the Brain link — jittered
    retries with a budget on the series/decision legs, single-attempt
    fire-and-forget on the mirror/event legs."""

    def _client(self, monkeypatch, fail_times=99):
        import dlrover_tpu.agent.master_client as mc

        c = BrainClient("127.0.0.1:1", "j", retries=3, retry_budget_s=30.0)
        calls = {"n": 0}

        def rpc(payload, timeout=None):
            calls["n"] += 1
            if calls["n"] <= fail_times:
                raise OSError("link down")
            return comm.serialize_message(comm.BaseResponse())

        monkeypatch.setattr(c._client, "_get_rpc", rpc)
        monkeypatch.setattr(c._client, "_report_rpc", rpc)
        monkeypatch.setattr(mc.random, "uniform", lambda a, b: 0.0)
        return c, calls

    def test_persist_metrics_retries_with_backoff(self, monkeypatch):
        c, calls = self._client(monkeypatch)
        with pytest.raises(ConnectionError):
            c.persist_metrics(_sample(2, 5.0))
        assert calls["n"] == 3

    def test_flaky_link_recovers_mid_call(self, monkeypatch):
        c, calls = self._client(monkeypatch, fail_times=1)
        c.persist_metrics(_sample(2, 5.0))  # 2nd attempt lands
        assert calls["n"] == 2
        c.poll_cluster_plan()  # the plan channel gets the same leg
        assert calls["n"] == 3  # healthy link: one attempt

    def test_event_legs_are_single_attempt(self, monkeypatch):
        c, calls = self._client(monkeypatch)
        with pytest.raises(ConnectionError):
            c.report_node_event(0, "h", "oom")
        assert calls["n"] == 1
        calls["n"] = 0
        with pytest.raises(ConnectionError):
            c.report_job_end("failed")
        assert calls["n"] == 1

    def test_retry_budget_bounds_the_tail(self, monkeypatch):
        import dlrover_tpu.agent.master_client as mc

        c = BrainClient(
            "127.0.0.1:1", "j", retries=10, retry_budget_s=0.0
        )
        calls = {"n": 0}

        def rpc(payload, timeout=None):
            calls["n"] += 1
            raise OSError("down")

        monkeypatch.setattr(c._client, "_get_rpc", rpc)
        monkeypatch.setattr(mc.random, "uniform", lambda a, b: 1.0)
        with pytest.raises(ConnectionError):
            c.optimize()
        assert calls["n"] == 1  # budget exhausted before any backoff


class TestScaleRequestEntry:
    def test_servicer_scale_request_drives_scale_to(self):
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.local_master import LocalJobMaster
        from dlrover_tpu.master.scaler import CallbackScaler

        m = LocalJobMaster(
            node_num=2, scaler=CallbackScaler(lambda plan: None)
        )
        m.prepare()
        c = MasterClient(m.addr, node_id=0)
        try:
            assert c.request_scale(4) is True
            assert m.auto_scaler.target == 4
        finally:
            c.close()
            m.stop()

    def test_scalerless_master_refuses_scale_request(self):
        """No platform scaler -> executing scale_to would fabricate
        ghost node entries nothing launches; the request is refused."""
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.local_master import LocalJobMaster

        m = LocalJobMaster(node_num=2)
        m.prepare()
        c = MasterClient(m.addr, node_id=0)
        try:
            assert c.request_scale(4) is False
            assert m.auto_scaler.target == 2
        finally:
            c.close()
            m.stop()


class TestBrainCtl:
    def _store(self, tmp_path):
        db = str(tmp_path / "brain.db")
        s = BrainServicer(db_path=db)
        _feed(s, "lin", [(2, 10 * 2**0.9), (4, 10 * 4**0.9)])
        sched = _scheduler(s, total_chips=8)
        v = sched.run_pass()
        sl = s.cluster_plan_slice("lin")
        s.record_plan_outcome(
            comm.PlanOutcomeReport(
                job_name="lin", version=v,
                worker_count=sl.worker_count,
                decision_to_resized_ms=17.5,
                realized_goodput_pct=96.0,
            )
        )
        s.record_node_event(
            comm.BrainNodeEventReport(
                job_name="lin", hostname="h1", event="straggler"
            )
        )
        s.close()
        return db

    def test_jobs_and_curves(self, tmp_path, capsys):
        from tools.brain_ctl import main

        db = self._store(tmp_path)
        assert main([db, "jobs"]) == 0
        out = capsys.readouterr().out
        assert "lin" in out and "goodput_pct" in out
        assert main([db, "curves", "--json"]) == 0
        import json

        rows = json.loads(capsys.readouterr().out)
        assert rows[0]["job"] == "lin"
        assert abs(rows[0]["b"] - 0.9) < 0.01
        assert rows[0]["points"]["4"] > rows[0]["points"]["2"]

    def test_plans_show_realized_outcome(self, tmp_path, capsys):
        """Acceptance: the realized-outcome feedback row is visible in
        brain_ctl output."""
        from tools.brain_ctl import main

        db = self._store(tmp_path)
        assert main([db, "plans", "--json"]) == 0
        import json

        rows = json.loads(capsys.readouterr().out)
        acked = [r for r in rows if r["status"] == "acked"]
        assert acked and acked[0]["decision_to_resized_ms"] == 17.5
        assert acked[0]["realized_goodput_pct"] == 96.0

    def test_events_and_missing_store(self, tmp_path, capsys):
        from tools.brain_ctl import main

        db = self._store(tmp_path)
        assert main([db, "events"]) == 0
        assert "straggler" in capsys.readouterr().out
        assert main([str(tmp_path / "nope.db"), "jobs"]) == 1


@pytest.mark.slow
def test_brain_bench_leg_gates():
    """The bench leg end to end: convergence beats the equal split,
    latency reported, accounting closed."""
    import bench

    results = {}
    bench.run_brain_bench(None, results, smoke=True)
    assert (
        results["brain_agg_goodput_closed"]
        > results["brain_agg_goodput_equal_split"]
    )
    assert results["brain_decision_to_resized_ms"] is not None
    assert results["brain_plans_unresolved"] == 0
    assert results["brain_plans_acked"] > 0
    assert results["brain_plans_expired"] > 0
    assert results["brain_outcome_rows"] > 0
