"""Elastic agent end-to-end tests: real master + real agent + real worker
subprocesses on localhost (parity with the reference's
test_elastic_training_agent.py pattern)."""

import os
import subprocess
import sys
import threading
import time

import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.training_agent import (
    ElasticTrainingAgent,
    WorkerSpec,
    WorkerState,
)
from dlrover_tpu.master.local_master import start_local_master

ASSETS = os.path.join(os.path.dirname(__file__), "assets")


@pytest.fixture()
def master():
    m = start_local_master(node_num=1)
    for mgr in m.rdzv_managers.values():
        mgr.update_rdzv_params(min_nodes=1, max_nodes=1, waiting_timeout=0)
    yield m
    m.stop()


def _make_agent(master, entrypoint, **spec_kw):
    client = MasterClient(master.addr, node_id=0)
    spec = WorkerSpec(
        entrypoint=os.path.join(ASSETS, entrypoint),
        nproc_per_node=spec_kw.pop("nproc_per_node", 1),
        max_restarts=spec_kw.pop("max_restarts", 2),
        monitor_interval=0.2,
        **spec_kw,
    )
    return ElasticTrainingAgent(node_rank=0, spec=spec, client=client)


class TestAgent:
    def test_success(self, master):
        agent = _make_agent(master, "exit0.py")
        result = agent.run()
        assert result.state == WorkerState.SUCCEEDED
        assert result.restarts == 0

    def test_restart_then_success(self, master):
        agent = _make_agent(master, "fail_once.py")
        result = agent.run()
        assert result.state == WorkerState.SUCCEEDED
        assert result.restarts == 1
        # the failure was reported to the master
        node = master.job_manager.get_node("worker", 0)

    def test_restart_budget_exhausted(self, master):
        agent = _make_agent(master, "fail_always.py", max_restarts=1)
        result = agent.run()
        assert result.state == WorkerState.FAILED
        assert result.restarts == 1
        assert "exitcode=3" in result.message

    def test_save_at_breakpoint_hook(self, master):
        agent = _make_agent(master, "fail_once.py")
        calls = []
        agent.set_checkpoint_hook(lambda: calls.append(1))
        result = agent.run()
        assert result.state == WorkerState.SUCCEEDED
        assert calls == [1]  # hook ran before the restart


class TestLauncher:
    def test_run_cli_single_proc(self, master):
        """dlrover-tpu-run against an existing master."""
        from dlrover_tpu.trainer import run as run_mod

        rc = run_mod.main(
            [
                "--nnodes=1",
                "--nproc-per-node=1",
                f"--master-addr={master.addr}",
                "--monitor-interval=0.2",
                os.path.join(ASSETS, "exit0.py"),
            ]
        )
        assert rc == 0

    @pytest.mark.slow
    def test_run_cli_distributed_training(self, master):
        """2 JAX processes rendezvous via master and psum across."""
        from dlrover_tpu.trainer import run as run_mod

        rc = run_mod.main(
            [
                "--nnodes=1",
                "--nproc-per-node=2",
                f"--master-addr={master.addr}",
                "--monitor-interval=0.5",
                "--device-spec=cpu:1",
                os.path.join(ASSETS, "toy_train.py"),
            ]
        )
        assert rc == 0

    @pytest.mark.slow
    def test_flash_ckpt_survives_preemption(self, master, tmp_path):
        """Worker flash-saves to memory only and dies hard at step 3; the
        agent persists shm before restarting, and the restarted worker
        resumes from step 3 (whole-stack Flash Checkpoint)."""
        from dlrover_tpu.ckpt.saver import AsyncCheckpointSaver
        from dlrover_tpu.trainer import run as run_mod

        AsyncCheckpointSaver.reset()
        ckpt_dir = str(tmp_path / "flash")
        os.environ["TEST_CKPT_DIR"] = ckpt_dir
        try:
            rc = run_mod.main(
                [
                    "--nnodes=1",
                    "--nproc-per-node=1",
                    f"--master-addr={master.addr}",
                    "--monitor-interval=0.3",
                    "--device-spec=cpu:1",
                    os.path.join(ASSETS, "ckpt_train.py"),
                ]
            )
        finally:
            os.environ.pop("TEST_CKPT_DIR", None)
            AsyncCheckpointSaver.reset()
        assert rc == 0


def test_enable_compile_cache(tmp_path, monkeypatch):
    import jax

    from dlrover_tpu.trainer.elastic.distributed import enable_compile_cache

    monkeypatch.setenv("DLROVER_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    got = enable_compile_cache()
    assert got == str(tmp_path / "cc")
    assert (tmp_path / "cc").is_dir()
    assert jax.config.jax_compilation_cache_dir == got

    monkeypatch.setenv("DLROVER_TPU_COMPILE_CACHE", "off")
    assert enable_compile_cache() == ""


def test_auto_configure(monkeypatch):
    from dlrover_tpu.trainer.run import auto_configure, parse_args

    monkeypatch.setenv("DLROVER_TPU_NODE_NUM", "4")
    args = parse_args(
        ["--auto-config", "--device-spec=cpu:8", "tests/assets/exit0.py"]
    )
    args = auto_configure(args)
    assert args.nnodes == "4"
    assert args.nproc_per_node == 8  # cpu:8 spec => static count
    assert args.network_check  # >= 4 nodes turns the check on

    monkeypatch.setenv("DLROVER_TPU_NODE_NUM", "2")
    args = parse_args(
        ["--auto-config", "--device-spec=cpu:2", "tests/assets/exit0.py"]
    )
    args = auto_configure(args)
    assert args.nnodes == "2" and not args.network_check

    # no platform env, CLI-provided --nnodes=8: the gate must fire off
    # the parsed min_nodes, not only the env-derived node count
    monkeypatch.delenv("DLROVER_TPU_NODE_NUM", raising=False)
    args = parse_args(
        [
            "--auto-config", "--nnodes=8", "--device-spec=cpu:2",
            "tests/assets/exit0.py",
        ]
    )
    args = auto_configure(args)
    assert args.network_check
