"""Auto-scaling + hang recovery: the elastic control loop closes.

Parity: the reference tests its auto-scaler against canned node tables
(test_job_auto_scaler.py) and treats hang as a relaunch trigger, not a
job failure.
"""

import threading
import time

import pytest

from dlrover_tpu.common.constants import (
    JobExitReason,
    NodeExitReason,
    NodeStatus,
)
from dlrover_tpu.common.global_context import Context
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.job_manager import NodeEvent
from dlrover_tpu.master.local_master import LocalJobMaster
from dlrover_tpu.master.scaler import (
    CallbackScaler,
    LocalProcessScaler,
    ScalePlan,
)

_ctx = Context.singleton_instance()


@pytest.fixture()
def master3():
    scaler = CallbackScaler(lambda plan: None)
    m = LocalJobMaster(node_num=3, scaler=scaler)
    # no gRPC server needed: these tests drive the managers directly
    yield m, scaler
    m.auto_scaler.stop()


def _set_running(master, node_id):
    node = master.job_manager.get_node("worker", node_id)
    node.update_status(NodeStatus.RUNNING)
    node.heartbeat_time = time.time()
    master.speed_monitor.add_running_worker(node_id)
    return node


class TestAutoScaler:
    def test_replaces_dead_node(self, master3):
        """A preempted/released node is replaced to restore world size."""
        master, scaler = master3
        for i in range(3):
            _set_running(master, i)
        dead = master.job_manager.get_node("worker", 1)
        dead.is_released = True
        dead.update_status(NodeStatus.FAILED)

        plan = master.auto_scaler.check_and_scale()
        assert len(plan.launch_nodes) == 1
        new = plan.launch_nodes[0]
        assert new.rank_index == 1  # takes over the dead node's rank
        assert len(master.auto_scaler.alive_nodes()) == 3
        assert scaler.plans  # the plan reached the platform scaler

    def test_exhausted_budget_stops_churn(self, master3):
        """A rank whose relaunch budget is spent is NOT replaced forever
        (otherwise a crash-looping node would be respawned every pass)."""
        master, _ = master3
        for i in range(3):
            _set_running(master, i)
        dead = master.job_manager.get_node("worker", 1)
        dead.relaunchable = False  # e.g. fatal user error
        dead.is_released = True
        dead.update_status(NodeStatus.FAILED)

        plan = master.auto_scaler.check_and_scale()
        assert plan.launch_nodes == []
        assert len(master.auto_scaler.alive_nodes()) == 2

    def test_poisoned_rank_does_not_starve_others(self, master3):
        """Rank 1 out of budget, rank 2 entitled: rank 2 must still be
        replaced (a break on the first exhausted rank would starve it)."""
        master, _ = master3
        for i in range(3):
            _set_running(master, i)
        poisoned = master.job_manager.get_node("worker", 1)
        poisoned.relaunchable = False
        poisoned.is_released = True
        poisoned.update_status(NodeStatus.FAILED)
        entitled = master.job_manager.get_node("worker", 2)
        entitled.is_released = True
        entitled.update_status(NodeStatus.FAILED)

        plan = master.auto_scaler.check_and_scale()
        assert [n.rank_index for n in plan.launch_nodes] == [2]

    def test_replacement_inherits_oom_memory_bump(self, master3):
        master, _ = master3
        for i in range(3):
            _set_running(master, i)
        dead = master.job_manager.get_node("worker", 1)
        dead.config_resource.memory_mb = 4096  # post-OOM doubled resource
        dead.is_released = True
        dead.update_status(NodeStatus.FAILED)

        plan = master.auto_scaler.check_and_scale()
        assert plan.launch_nodes[0].config_resource.memory_mb == 4096

    def test_heartbeat_timeout_node_is_replaced(self, master3):
        master, scaler = master3
        for i in range(3):
            _set_running(master, i)
        stale = master.job_manager.get_node("worker", 2)
        stale.heartbeat_time = time.time() - 10_000

        plan = master.auto_scaler.check_and_scale()
        assert stale.is_released
        assert [n.id for n in plan.remove_nodes] == [2]
        assert len(plan.launch_nodes) == 1
        assert len(master.auto_scaler.alive_nodes()) == 3

    def test_scale_to_shrinks_and_grows(self, master3):
        master, scaler = master3
        for i in range(3):
            _set_running(master, i)
        plan = master.scale_to(1)
        assert len(plan.remove_nodes) == 2
        assert len(master.auto_scaler.alive_nodes()) == 1

        plan = master.scale_to(3)
        assert len(plan.launch_nodes) == 2
        assert len(master.auto_scaler.alive_nodes()) == 3

    def test_relaunch_goes_through_scaler(self, master3):
        """A recoverable failure relaunches via the Scaler seam."""
        master, scaler = master3
        node = _set_running(master, 0)
        failed = Node(node_type="worker", node_id=0)
        failed.exit_reason = NodeExitReason.HARDWARE_ERROR
        failed.status = NodeStatus.FAILED
        master.job_manager.process_event(NodeEvent("modified", failed))
        assert scaler.plans
        last = scaler.plans[-1]
        assert [n.id for n in last.remove_nodes] == [0]
        assert len(last.launch_nodes) == 1


class TestLocalProcessScaler:
    def test_spawn_and_remove(self):
        spawned = []
        s = LocalProcessScaler(
            "127.0.0.1:1", ["train.py"], spawn_fn=spawned.append
        )
        n = Node(node_type="worker", node_id=5, rank_index=2)
        s.scale(ScalePlan(launch_nodes=[n]))
        assert spawned == [n]
        cmd = s.command_for(n)
        assert "--node-rank=2" in cmd and "train.py" in cmd
        s.stop()


class TestHangRecovery:
    def test_hang_restarts_workers_then_survives(self, master3):
        """Hang → restart order via heartbeat channel; job keeps running
        (the reference's behavior; VERDICT weak #6: exiting is the
        anti-goodput outcome)."""
        master, _ = master3
        node = _set_running(master, 0)
        old_timeout = _ctx.hang_detection_secs
        _ctx.hang_detection_secs = 0.1
        try:
            master.speed_monitor.set_start_timestamp()
            master.speed_monitor._start_training_time = time.time() - 60
            assert master.speed_monitor.all_worker_hanged()

            box = {}
            t = threading.Thread(
                target=lambda: box.update(
                    reason=master.run(max_hang_recoveries=2)
                )
            )
            t.start()
            time.sleep(0.5)
            # first recovery must have fired: restart flag consumed via
            # the heartbeat channel, job still alive
            action = master.job_manager.collect_node_heartbeat("worker", 0)
            assert action == "restart"
            assert t.is_alive() or box.get("reason") != JobExitReason.SUCCEEDED
            # let recoveries exhaust -> HANG_ERROR exit (still no progress)
            t.join(timeout=30)
            assert not t.is_alive()
            assert box["reason"] == JobExitReason.HANG_ERROR
        finally:
            _ctx.hang_detection_secs = old_timeout
            master.stop()

    def test_progress_clears_hang_counter(self, master3):
        master, _ = master3
        _set_running(master, 0)
        old_timeout = _ctx.hang_detection_secs
        _ctx.hang_detection_secs = 30
        try:
            master.speed_monitor.collect_global_step(10)
            assert not master.speed_monitor.all_worker_hanged()
        finally:
            _ctx.hang_detection_secs = old_timeout
