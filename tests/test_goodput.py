"""Goodput ledger, crash flight recorder, worker-command channel, and
the cross-worker timeline merge (ISSUE 7).

Acceptance anchors:
- the ledger partitions wall time into the closed taxonomy with zero
  closure error on synthetic and live-span inputs (the ±1% smoke gate
  is the bench twin of these tests);
- the fleet goodput number flows worker scalars → TelemetryAggregator
  → JobMetricCollector sample → Brain datastore (including schema
  migration of pre-goodput stores);
- an exception'd dump produces a complete bundle whose trace validates
  as Chrome JSON, and the hang watchdog dumps once per episode from
  its own thread;
- master-queued worker commands coalesce, drain exactly once, relay
  through the agent's command file, and execute idempotently in the
  trainer's poll;
- ``tools/merge_timeline.py`` re-bases per-worker traces onto one
  wall-clock axis and overlays master node events.
"""

import json
import os
import sqlite3
import threading
import time
from types import SimpleNamespace

import pytest

from dlrover_tpu.obs import flight_recorder as obs_flight
from dlrover_tpu.obs import goodput as obs_goodput
from dlrover_tpu.obs.flight_recorder import FlightRecorder, ProfilerCapture
from dlrover_tpu.obs.goodput import (
    CATEGORIES,
    GoodputLedger,
    GoodputReport,
    _merge,
    _subtract,
    compute_goodput_pct,
)
from dlrover_tpu.obs.metrics import MetricsRegistry
from dlrover_tpu.obs.trace import SpanTracer, validate_chrome_trace

MS = 1_000_000  # ns


def _put(tracer, name, start_ns, dur_ns, tid=1, depth=0):
    """Append one synthetic completed record (the drain/ledger input
    shape) without threading real sleeps through the hot path."""
    tracer._buf.append(
        (name, tid, start_ns, dur_ns, depth, None, next(tracer._seq))
    )
    tracer._appended += 1


class TestIntervalOps:
    def test_merge_sorts_and_coalesces(self):
        assert _merge([(5, 9), (0, 3), (2, 4), (9, 9)]) == [(0, 4), (5, 9)]

    def test_subtract_splits_and_clips(self):
        ivs = [(0, 10)]
        cover = [(2, 4), (6, 8)]
        assert _subtract(ivs, cover) == [(0, 2), (4, 6), (8, 10)]

    def test_subtract_total_cover(self):
        assert _subtract([(1, 5)], [(0, 10)]) == []

    def test_goodput_formula(self):
        assert compute_goodput_pct(30.0, 60.0) == 50.0
        assert compute_goodput_pct(1.0, 0.0) == 0.0
        assert compute_goodput_pct(-1.0, 10.0) == 0.0


class TestGoodputLedger:
    def _ledger(self, **kw):
        tr = SpanTracer(enabled=True)
        led = GoodputLedger(tracer=tr, **kw)
        # rewind the epoch 1s so synthetic records laid out "in the
        # past" fall inside the collectable window even when a test
        # snapshots with the real clock
        led._t0_ns -= 1_000 * MS
        led._last_ns -= 1_000 * MS
        t0 = led._last_ns
        return tr, led, t0

    def test_span_categories_attributed(self):
        tr, led, t0 = self._ledger()
        _put(tr, "compute", t0 + 10 * MS, 100 * MS)
        _put(tr, "data_wait", t0 + 120 * MS, 50 * MS)
        _put(tr, "ckpt_commit", t0 + 180 * MS, 40 * MS)
        rep = led.snapshot(now_ns=t0 + 300 * MS)
        assert rep.seconds["productive_compute"] == pytest.approx(0.100)
        assert rep.seconds["data_stall"] == pytest.approx(0.050)
        assert rep.seconds["ckpt_block"] == pytest.approx(0.040)
        assert rep.seconds["other"] == pytest.approx(0.110)
        assert rep.closure_error_pct == pytest.approx(0.0)

    def test_priority_makes_partition_disjoint(self):
        """ckpt_block outranks productive_compute: the overlapped part
        is claimed once, by the higher category."""
        tr, led, t0 = self._ledger()
        _put(tr, "compute", t0, 100 * MS)
        _put(tr, "ckpt_stage", t0 + 50 * MS, 100 * MS)  # overlaps 50ms
        rep = led.snapshot(now_ns=t0 + 200 * MS)
        assert rep.seconds["ckpt_block"] == pytest.approx(0.100)
        assert rep.seconds["productive_compute"] == pytest.approx(0.050)
        total = sum(rep.seconds.values())
        assert total == pytest.approx(rep.wall_s)

    def test_unknown_spans_land_in_other(self):
        tr, led, t0 = self._ledger()
        _put(tr, "eval", t0, 50 * MS)
        rep = led.snapshot(now_ns=t0 + 100 * MS)
        assert rep.seconds["other"] == pytest.approx(0.100)

    def test_tid_filter_ignores_other_threads(self):
        """The prefetcher's h2d overlaps compute by design — only the
        train thread's spans may claim wall time."""
        tr, led, t0 = self._ledger(tid_fn=lambda: 1)
        _put(tr, "compute", t0, 50 * MS, tid=1)
        _put(tr, "compute", t0, 80 * MS, tid=2)  # producer thread
        rep = led.snapshot(now_ns=t0 + 100 * MS)
        assert rep.seconds["productive_compute"] == pytest.approx(0.050)

    def test_incremental_collect_never_double_counts(self):
        tr, led, t0 = self._ledger()
        _put(tr, "compute", t0, 40 * MS)
        led.collect(now_ns=t0 + 50 * MS)
        led.collect(now_ns=t0 + 60 * MS)  # same records still in ring
        rep = led.snapshot(now_ns=t0 + 100 * MS)
        assert rep.seconds["productive_compute"] == pytest.approx(0.040)

    def test_span_straddling_two_windows_clipped(self):
        tr, led, t0 = self._ledger()
        led.collect(now_ns=t0 + 50 * MS)  # window 1 ends mid-span
        _put(tr, "compute", t0 + 30 * MS, 60 * MS)  # lands after
        rep = led.snapshot(now_ns=t0 + 100 * MS)
        # only the [50,90) part falls in an uncounted window
        assert rep.seconds["productive_compute"] == pytest.approx(0.040)

    def test_open_span_counted_live_then_not_double_counted(self):
        """A wedged ckpt_commit shows up WHILE stuck; when it finally
        completes, the already-claimed window is not recounted."""
        tr, led, t0 = self._ledger()
        sp = tr.span("ckpt_commit")
        time.sleep(0.04)
        led.collect()
        with led._lock:
            mid = led._seconds["ckpt_block"]
        assert mid >= 0.03
        time.sleep(0.02)
        sp.end()
        rep = led.snapshot()
        dur = rep.seconds["ckpt_block"]
        assert dur >= mid
        assert dur <= rep.wall_s
        assert rep.closure_error_pct == pytest.approx(0.0, abs=1e-6)

    def test_replay_and_degraded_episodes(self):
        _, led, _ = self._ledger()
        led.replay_begin()
        time.sleep(0.03)
        led.replay_end()
        led.degraded_enter()
        time.sleep(0.02)
        led.degraded_exit()
        rep = led.snapshot()
        assert rep.seconds["restart_replay"] >= 0.025
        assert rep.seconds["degraded"] >= 0.015
        assert rep.closure_error_pct == pytest.approx(0.0, abs=1e-6)

    def test_live_episode_counted_while_open(self):
        _, led, _ = self._ledger()
        led.degraded_enter()
        time.sleep(0.03)
        rep = led.snapshot()
        assert rep.seconds["degraded"] >= 0.025
        # still open: the NEXT window keeps accruing without recount
        time.sleep(0.02)
        rep2 = led.snapshot()
        assert rep2.seconds["degraded"] >= rep.seconds["degraded"] + 0.015
        led.degraded_exit()

    def test_mark_interval_validates_category(self):
        _, led, _ = self._ledger()
        time.sleep(0.02)
        # a fully-elapsed interval (future portions are clipped to
        # "now" and carried into the next window)
        t = time.monotonic_ns() - 15 * MS
        led.mark_interval("restart_replay", t, t + 10 * MS)
        with pytest.raises(ValueError):
            led.mark_interval("productive_compute", t, t + MS)
        rep = led.snapshot()
        assert rep.seconds["restart_replay"] == pytest.approx(0.010)

    def test_export_publishes_gauges(self):
        tr, led, t0 = self._ledger()
        _put(tr, "compute", t0, 50 * MS)
        reg = MetricsRegistry()
        led.export(reg)
        scalars = reg.scalars()
        assert "dlrover_goodput_pct" in scalars
        assert "dlrover_goodput_wall_seconds" in scalars
        key = 'dlrover_goodput_seconds_total{category="productive_compute"}'
        assert scalars[key] == pytest.approx(0.050, abs=0.02)
        for cat in CATEGORIES:
            assert (
                f'dlrover_goodput_seconds_total{{category="{cat}"}}'
                in scalars
            )

    def test_note_degraded_seam(self, monkeypatch):
        _, led, _ = self._ledger()
        monkeypatch.setattr(obs_goodput, "_default", None)
        obs_goodput.note_degraded(True)  # no ledger: must not raise
        obs_goodput.install_default_ledger(led)
        obs_goodput.note_degraded(True)
        time.sleep(0.02)
        obs_goodput.note_degraded(False)
        assert led.snapshot().seconds["degraded"] >= 0.015

    def test_saver_degraded_exit_closes_ledger_episode(self):
        """The recovery side of the PR-5 seam: leaving degraded mode
        must close the ledger episode, or every second after recovery
        books as 'degraded' forever."""
        from dlrover_tpu.ckpt.saver import AsyncCheckpointSaver
        from dlrover_tpu.obs.goodput import install_default_ledger

        AsyncCheckpointSaver.reset()
        saver = AsyncCheckpointSaver.start_async_saving_ckpt(
            local_shard_num=1
        )
        try:
            led = GoodputLedger(tracer=SpanTracer(enabled=True))
            install_default_ledger(led)
            saver._degraded = True
            led.degraded_enter()  # what the entry hook did
            time.sleep(0.02)
            saver._exit_degraded(5)
            assert led._degraded_since is None
            booked = led.snapshot().seconds["degraded"]
            assert booked >= 0.015
            time.sleep(0.02)  # recovered: no further accrual
            assert led.snapshot().seconds["degraded"] == pytest.approx(
                booked, abs=1e-6
            )
        finally:
            AsyncCheckpointSaver.reset()

    def test_report_shapes(self):
        rep = GoodputReport(
            wall_s=10.0, seconds={"productive_compute": 5.0, "other": 5.0}
        )
        assert rep.goodput_pct == 50.0
        d = rep.as_dict()
        assert d["wall_s"] == 10.0 and d["goodput_pct"] == 50.0


class TestDrainAndWraparound:
    def test_drain_cursor_chain(self):
        tr = SpanTracer(enabled=True)
        for i in range(5):
            _put(tr, "compute", i, 1)
        recs, cur, dropped = tr.drain(0)
        assert len(recs) == 5 and dropped == 0
        for i in range(3):
            _put(tr, "compute", 10 + i, 1)
        recs2, cur2, dropped2 = tr.drain(cur)
        assert len(recs2) == 3 and dropped2 == 0
        assert tr.drain(cur2) == ([], cur2, 0)

    def test_drain_reports_lapped_records(self):
        tr = SpanTracer(enabled=True, capacity=16)
        for i in range(4):
            _put(tr, "compute", i, 1)
        _, cur, _ = tr.drain(0)
        for i in range(40):  # laps the 16-slot ring
            _put(tr, "compute", 100 + i, 1)
        recs, _, dropped = tr.drain(cur)
        assert len(recs) == 16
        assert dropped == 40 - 16

    def test_concurrent_export_no_torn_or_duplicate_records(self):
        """The satellite: the hot path lapping the exporter mid-drain
        must never tear a record or deliver one twice — every drained
        seq is unique, in order, and records+dropped accounts for
        every append."""
        tr = SpanTracer(enabled=True, capacity=64)
        stop = threading.Event()
        # prime the cursor chain: a cursor of 0 means "fresh consumer,
        # history is a starting point, not a loss" — the accounting
        # below needs the chain to start before the producers do
        _put(tr, "compute", 0, 1)
        seen = []
        recs, cursor, _ = tr.drain(0)
        seen.extend(r[6] for r in recs)

        def hot_path():
            while not stop.is_set():
                sp = tr.span("compute")
                sp.end()

        producers = [
            threading.Thread(target=hot_path, daemon=True)
            for _ in range(2)
        ]
        for p in producers:
            p.start()
        dropped_total = 0
        deadline = time.time() + 0.5
        while time.time() < deadline:
            recs, cursor, dropped = tr.drain(cursor)
            dropped_total += dropped
            seen.extend(r[6] for r in recs)
            for r in recs:
                assert len(r) == 7 and r[0] == "compute"  # not torn
        stop.set()
        for p in producers:
            p.join(timeout=2)
        assert len(seen) == len(set(seen)), "duplicated records"
        assert seen == sorted(seen), "out-of-order delivery"
        # exactly-once accounting over the whole run: everything ever
        # appended was either delivered or reported dropped (modulo
        # the tail still sitting in the ring)
        recs, cursor, dropped = tr.drain(cursor)
        seen.extend(r[6] for r in recs)
        dropped_total += dropped
        assert len(seen) + dropped_total == cursor

    def test_open_span_records_raw_timestamps(self):
        tr = SpanTracer(enabled=True)
        sp = tr.span("ckpt_commit")
        try:
            recs = tr.open_span_records()
            assert len(recs) == 1
            name, tid, start_ns, depth = recs[0]
            assert name == "ckpt_commit"
            assert tid == threading.get_ident()
            assert start_ns <= time.monotonic_ns()
        finally:
            sp.end()
        assert tr.open_span_records() == []


class TestHangAttributionHeartbeat:
    """Satellite: hang attribution when the heartbeat file is missing
    or stale."""

    class _FakeClient:
        def __init__(self):
            self.steps = []
            self.metric_calls = []

        def report_global_step(self, step):
            self.steps.append(step)

        def report_train_metrics(self, step, metrics, **kw):
            self.metric_calls.append((step, dict(metrics), kw))

    def test_missing_heartbeat_file_reports_nothing(
        self, tmp_path, monkeypatch
    ):
        from dlrover_tpu.agent.monitor import (
            TrainingMonitor,
            read_runtime_metrics,
        )

        path = str(tmp_path / "nope" / "metrics.json")
        monkeypatch.setenv("DLROVER_TPU_RUNTIME_METRICS_PATH", path)
        assert read_runtime_metrics(path) == {}
        client = self._FakeClient()
        mon = TrainingMonitor(client, interval=999)
        mon._tick()  # must not raise, must not report
        assert client.steps == [] and client.metric_calls == []

    def test_stale_heartbeat_stops_forwarding(
        self, tmp_path, monkeypatch
    ):
        """An unchanged payload timestamp (trainer AND heartbeat dead)
        must not keep re-forwarding the last snapshot."""
        from dlrover_tpu.agent.monitor import (
            TrainingMonitor,
            report_runtime_metrics,
        )

        path = str(tmp_path / "metrics.json")
        monkeypatch.setenv("DLROVER_TPU_RUNTIME_METRICS_PATH", path)
        client = self._FakeClient()
        mon = TrainingMonitor(client, interval=999)
        report_runtime_metrics(4, loss=1.0, span_heartbeat_ts=123.0)
        mon._tick()
        assert len(client.metric_calls) == 1
        mon._tick()  # file untouched since: stale
        mon._tick()
        assert len(client.metric_calls) == 1

    def test_attribution_without_any_span_report(self):
        from dlrover_tpu.obs.aggregate import TelemetryAggregator

        agg = TelemetryAggregator()
        # the worker reports steps but its heartbeat never published an
        # open span (missing heartbeat file on that host)
        agg.observe_step_report(3, 7, 1000.0)
        assert agg.hang_attribution() == {3: "no open span reported"}
        assert "worker 3 no open span reported" in agg.describe_hang()

    def test_stale_open_span_elapsed_keeps_advancing(self):
        """A worker that reported 'stuck in ckpt_commit for 10s' and
        then went silent is MORE stuck now, not frozen at 10s."""
        from dlrover_tpu.obs.aggregate import TelemetryAggregator

        agg = TelemetryAggregator()
        agg.observe_metrics(
            1, 5, {}, open_span="ckpt_commit", open_span_elapsed_s=10.0
        )
        time.sleep(0.05)
        name, elapsed = agg.last_open_span(1)
        assert name == "ckpt_commit"
        assert elapsed > 10.0
        assert "stuck in ckpt_commit" in agg.describe_hang()

    def test_empty_aggregator_describe_hang(self):
        from dlrover_tpu.obs.aggregate import TelemetryAggregator

        assert (
            TelemetryAggregator().describe_hang()
            == "no per-worker telemetry"
        )


class TestFlightRecorder:
    def _recorder(self, tmp_path, **kw):
        tr = SpanTracer(enabled=True)
        with tr.span("compute"):
            pass
        reg = MetricsRegistry()
        reg.gauge("dlrover_test_gauge", "g").set(1.0)
        rec = FlightRecorder(
            base_dir=str(tmp_path), tracer=tr, registry=reg,
            identity={"node_id": 3}, **kw,
        )
        return tr, reg, rec

    def test_dump_writes_complete_bundle(self, tmp_path):
        tr, reg, rec = self._recorder(tmp_path)
        rec.note_event("fault", "injected enospc")
        bundle = rec.dump("crash", exc=ValueError("boom"))
        assert bundle is not None and os.path.isdir(bundle)
        files = set(os.listdir(bundle))
        assert files == {
            "manifest.json", "trace.json", "metrics.prom",
            "stacks.txt", "events.json",
        }
        with open(os.path.join(bundle, "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["reason"] == "crash"
        assert manifest["identity"]["node_id"] == 3
        assert manifest["exception"]["type"] == "ValueError"
        assert "boom" in manifest["exception"]["message"]
        with open(os.path.join(bundle, "trace.json")) as f:
            ok, reason = validate_chrome_trace(json.load(f))
        assert ok, reason
        with open(os.path.join(bundle, "events.json")) as f:
            events = json.load(f)
        assert events[-1]["kind"] == "fault"
        with open(os.path.join(bundle, "stacks.txt")) as f:
            stacks = f.read()
        assert "MainThread" in stacks
        with open(os.path.join(bundle, "metrics.prom")) as f:
            assert "dlrover_test_gauge" in f.read()
        assert rec.dumps == [bundle]

    def test_rate_limit_folds_double_triggers(self, tmp_path):
        _, _, rec = self._recorder(tmp_path)
        first = rec.dump("hang")
        assert first is not None
        assert rec.dump("crash") is None  # < MIN_DUMP_INTERVAL_S later
        forced = rec.dump("crash", force=True)
        assert forced is not None and forced != first

    def test_open_span_lands_in_manifest(self, tmp_path):
        tr, _, rec = self._recorder(tmp_path)
        sp = tr.span("ckpt_commit")
        try:
            bundle = rec.dump("hang")
        finally:
            sp.end()
        with open(os.path.join(bundle, "manifest.json")) as f:
            manifest = json.load(f)
        assert any(
            s["name"] == "ckpt_commit" for s in manifest["open_spans"]
        )

    def test_watchdog_dumps_once_per_episode(self, tmp_path):
        tr, _, rec = self._recorder(tmp_path)
        sp = tr.span("ckpt_commit")
        # fake a 200s-old wedge: the watchdog must fire on its own
        # daemon thread — the "train thread" is conceptually stuck
        sp.start_ns -= 200_000_000_000
        try:
            rec.start_watchdog(hang_dump_after_s=60, interval_s=0.02)
            deadline = time.time() + 2
            while time.time() < deadline and not rec.dumps:
                time.sleep(0.02)
            assert len(rec.dumps) == 1
            time.sleep(0.2)  # same episode: no second dump
            assert len(rec.dumps) == 1
            assert any(e["kind"] == "hang" for e in rec.events())
        finally:
            rec.stop_watchdog()
            sp.end()

    def test_watchdog_quiet_below_threshold(self, tmp_path):
        tr, _, rec = self._recorder(tmp_path)
        sp = tr.span("compute")
        try:
            rec.start_watchdog(hang_dump_after_s=60, interval_s=0.02)
            time.sleep(0.15)
            assert rec.dumps == []
        finally:
            rec.stop_watchdog()
            sp.end()

    def test_degraded_note_event_triggers_dump(
        self, tmp_path, monkeypatch
    ):
        _, _, rec = self._recorder(tmp_path)
        monkeypatch.setattr(obs_flight, "_default", rec)
        obs_flight.note_event("ckpt_degraded", "step 9: enospc")
        assert len(rec.dumps) == 1
        obs_flight.note_event("restart", "not a dump trigger")
        assert len(rec.dumps) == 1
        assert [e["kind"] for e in rec.events()] == [
            "ckpt_degraded", "restart",
        ]

    def test_flight_dir_env_resolved_per_dump(
        self, tmp_path, monkeypatch
    ):
        tr = SpanTracer(enabled=True)
        rec = FlightRecorder(tracer=tr, registry=MetricsRegistry())
        monkeypatch.setenv(
            obs_flight.ENV_FLIGHT_DIR, str(tmp_path / "redirected")
        )
        bundle = rec.dump("manual")
        assert bundle is not None
        assert bundle.startswith(str(tmp_path / "redirected"))


class TestProfilerCapture:
    def _patched(self, monkeypatch, tmp_path):
        import jax

        calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace", lambda d: calls.append(("start", d))
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append(("stop",))
        )
        return calls, ProfilerCapture(out_root=str(tmp_path))

    def test_capture_spans_k_steps(self, monkeypatch, tmp_path):
        calls, cap = self._patched(monkeypatch, tmp_path)
        assert cap.request(2, reason="straggler")
        assert not cap.request(2)  # already pending
        cap.on_step_begin()
        assert cap.active
        assert calls[0][0] == "start"
        cap.on_step_end()
        assert cap.active  # 1 of 2 steps done
        cap.on_step_end()
        assert not cap.active
        assert calls[-1] == ("stop",)
        assert len(cap.artifacts) == 1
        assert "straggler" in cap.artifacts[0]

    def test_cooldown_refuses_rerequest(self, monkeypatch, tmp_path):
        calls, cap = self._patched(monkeypatch, tmp_path)
        cap._cooldown_s = 300.0
        assert cap.request(1)
        cap.on_step_begin()
        cap.on_step_end()
        assert not cap.request(1)  # cooling down
        cap._cooldown_s = 0.0
        assert cap.request(1)

    def test_bad_steps_refused(self, monkeypatch, tmp_path):
        _, cap = self._patched(monkeypatch, tmp_path)
        assert not cap.request(0)
        assert not cap.request(-3)

    def test_abort_stops_live_capture(self, monkeypatch, tmp_path):
        calls, cap = self._patched(monkeypatch, tmp_path)
        cap.request(5)
        cap.on_step_begin()
        cap.abort()
        assert not cap.active
        assert calls[-1] == ("stop",)
        assert cap.artifacts == []  # aborted ≠ delivered


class TestWorkerCommandChannel:
    def _servicer(self):
        from dlrover_tpu.master.servicer import MasterServicer

        return MasterServicer()

    def test_queue_assigns_monotonic_ids_and_coalesces(self):
        s = self._servicer()
        c1 = s.queue_worker_command(0, "flight_dump", reason="hang")
        c2 = s.queue_worker_command(0, "flight_dump", reason="hang")
        c3 = s.queue_worker_command(0, "profile", arg=3, reason="straggler")
        c4 = s.queue_worker_command(1, "flight_dump", reason="hang")
        assert c1.id == c2.id  # coalesced while pending
        assert c3.id > c1.id and c4.id > c3.id

    def test_coalesce_takes_newest_arg(self):
        s = self._servicer()
        s.queue_worker_command(0, "profile", arg=3, reason="straggler")
        c = s.queue_worker_command(0, "profile", arg=20, reason="straggler")
        assert c.arg == 20  # the 20-step request must not shrink to 3

    def test_dispatch_redelivers_until_acked(self):
        """A lost RESPONSE must not drop a command: delivery without an
        ack redelivers; the ack (the next poll's ack_id) clears."""
        from dlrover_tpu.common import comm

        s = self._servicer()
        cmd = s.queue_worker_command(2, "profile", arg=5, reason="straggler")
        req = comm.BaseRequest(node_id=2)
        got = s._dispatch_get(req, comm.WorkerCommandRequest())
        assert isinstance(got, comm.WorkerCommands)
        assert [c.kind for c in got.commands] == ["profile"]
        assert got.commands[0].arg == 5
        # un-acked re-poll (the agent never saw the response): SAME
        # command comes back instead of vanishing
        again = s._dispatch_get(req, comm.WorkerCommandRequest())
        assert [c.id for c in again.commands] == [cmd.id]
        # acked poll clears it, and re-queueing works afterwards
        acked = s._dispatch_get(
            req, comm.WorkerCommandRequest(ack_id=cmd.id)
        )
        assert acked.commands == []
        s.queue_worker_command(2, "profile", arg=5, reason="straggler")
        assert len(
            s._dispatch_get(
                req, comm.WorkerCommandRequest(ack_id=cmd.id)
            ).commands
        ) == 1

    def test_no_coalesce_into_delivered_command(self):
        """A request arriving after delivery (but before the ack) must
        get a FRESH id — the trainer dedups by id, so folding into the
        delivered command would silently drop the new request."""
        from dlrover_tpu.common import comm

        s = self._servicer()
        c1 = s.queue_worker_command(0, "profile", arg=3, reason="straggler")
        req = comm.BaseRequest(node_id=0)
        s._dispatch_get(req, comm.WorkerCommandRequest())  # delivered
        c2 = s.queue_worker_command(0, "profile", arg=3, reason="straggler")
        assert c2.id > c1.id
        # both ride the next (still un-acked) poll
        got = s._dispatch_get(req, comm.WorkerCommandRequest())
        assert [c.id for c in got.commands] == [c1.id, c2.id]

    def test_clear_worker_commands_purges_queue(self):
        """The pre-restart purge: a pending command targets the dying
        incarnation and must not reach its replacement."""
        from dlrover_tpu.common import comm

        s = self._servicer()
        s.queue_worker_command(0, "flight_dump", reason="hang")
        s.queue_worker_command(1, "flight_dump", reason="hang")
        s.clear_worker_commands(1)
        req1 = comm.BaseRequest(node_id=1)
        assert s._dispatch_get(req1, comm.WorkerCommandRequest()).commands == []
        s.clear_worker_commands()
        req0 = comm.BaseRequest(node_id=0)
        assert s._dispatch_get(req0, comm.WorkerCommandRequest()).commands == []
        # the channel still works after a purge
        s.queue_worker_command(0, "flight_dump", reason="hang")
        assert len(
            s._dispatch_get(req0, comm.WorkerCommandRequest()).commands
        ) == 1

    def test_dispatch_explicit_node_id_wins(self):
        from dlrover_tpu.common import comm

        s = self._servicer()
        s.queue_worker_command(7, "flight_dump", reason="hang")
        got = s._dispatch_get(
            comm.BaseRequest(node_id=0),
            comm.WorkerCommandRequest(node_id=7),
        )
        assert len(got.commands) == 1

    def test_relay_mirrors_commands_to_file(self, tmp_path, monkeypatch):
        from dlrover_tpu.agent.monitor import (
            WorkerCommandRelay,
            read_worker_commands,
        )
        from dlrover_tpu.common import comm

        path = str(tmp_path / "cmds.json")
        monkeypatch.setenv("DLROVER_TPU_WORKER_COMMANDS_PATH", path)

        class _Client:
            def __init__(self):
                self.acks = []
                self.queue = [
                    comm.WorkerCommand(
                        id=1, kind="flight_dump", reason="hang"
                    ),
                    comm.WorkerCommand(
                        id=2, kind="profile", arg=3, reason="straggler"
                    ),
                ]

            def poll_worker_commands(self, ack_id=0):
                self.acks.append(ack_id)
                return [c for c in self.queue if c.id > ack_id]

        client = _Client()
        relay = WorkerCommandRelay(client, interval=999, keep=3)
        relay._tick()
        cmds = read_worker_commands(path)
        assert [c["kind"] for c in cmds] == ["flight_dump", "profile"]
        relay._tick()  # everything acked: file untouched
        assert read_worker_commands(path) == cmds
        assert client.acks == [0, 2]  # the second poll acked id 2

    def test_relay_dedups_unacked_redelivery(
        self, tmp_path, monkeypatch
    ):
        """The master redelivers until acked; the relay must not write
        the same command into the file twice."""
        from dlrover_tpu.agent.monitor import (
            WorkerCommandRelay,
            read_worker_commands,
        )
        from dlrover_tpu.common import comm

        path = str(tmp_path / "cmds.json")

        class _Client:
            def poll_worker_commands(self, ack_id=0):
                # a master that never sees the ack: always redelivers
                return [comm.WorkerCommand(id=1, kind="flight_dump")]

        relay = WorkerCommandRelay(
            _Client(), interval=999, path=path, keep=8
        )
        relay._tick()
        relay._tick()
        assert [c["id"] for c in read_worker_commands(path)] == [1]

    def test_relay_keeps_bounded_tail(self, tmp_path):
        from dlrover_tpu.agent.monitor import (
            WorkerCommandRelay,
            read_worker_commands,
        )
        from dlrover_tpu.common import comm

        path = str(tmp_path / "cmds.json")

        class _Client:
            def __init__(self):
                self.n = 0

            def poll_worker_commands(self, ack_id=0):
                self.n += 1
                return [
                    comm.WorkerCommand(id=self.n, kind="flight_dump")
                ]

        relay = WorkerCommandRelay(
            _Client(), interval=999, path=path, keep=2
        )
        for _ in range(4):
            relay._tick()
        cmds = read_worker_commands(path)
        assert [c["id"] for c in cmds] == [3, 4]

    def test_read_worker_commands_missing_or_garbage(self, tmp_path):
        from dlrover_tpu.agent.monitor import read_worker_commands

        assert read_worker_commands(str(tmp_path / "nope.json")) == []
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert read_worker_commands(str(bad)) == []

    def test_trainer_poll_executes_each_command_once(
        self, tmp_path, monkeypatch
    ):
        """The trainer-side executor, run against a stub: a flight_dump
        dumps, a profile arms the capture, and re-polling the same file
        is a no-op (master-monotonic ids)."""
        from dlrover_tpu.agent.monitor import atomic_write_json
        from dlrover_tpu.trainer.elastic.trainer import ElasticTrainer

        path = str(tmp_path / "cmds.json")
        monkeypatch.setenv("DLROVER_TPU_WORKER_COMMANDS_PATH", path)
        tr = SpanTracer(enabled=True)
        with tr.span("compute"):
            pass
        rec = FlightRecorder(
            base_dir=str(tmp_path / "flight"), tracer=tr,
            registry=MetricsRegistry(),
        )
        requested = []
        cap = SimpleNamespace(
            request=lambda steps, reason="": (
                requested.append((steps, reason)) or True
            )
        )
        stub = SimpleNamespace(
            _last_command_id=0, _flight=rec, _profiler_capture=cap
        )
        atomic_write_json(path, {"commands": [
            {"id": 1, "kind": "flight_dump", "arg": 0, "reason": "hang"},
            {"id": 2, "kind": "profile", "arg": 4, "reason": "straggler"},
            {"id": 3, "kind": "bogus", "arg": 0, "reason": ""},
        ]})
        ElasticTrainer._poll_worker_commands(stub)
        assert len(rec.dumps) == 1
        assert "request_hang" in rec.dumps[0]
        assert requested == [(4, "straggler")]
        assert stub._last_command_id == 3
        ElasticTrainer._poll_worker_commands(stub)  # same file again
        assert len(rec.dumps) == 1 and len(requested) == 1


class TestAggregatorGoodput:
    def _scalars(self, productive, wall, **extra):
        s = {
            "dlrover_goodput_wall_seconds": wall,
            'dlrover_goodput_seconds_total{category="productive_compute"}':
                productive,
        }
        for cat, v in extra.items():
            s[f'dlrover_goodput_seconds_total{{category="{cat}"}}'] = v
        return s

    def test_worker_goodput_from_metrics_report(self):
        from dlrover_tpu.obs.aggregate import TelemetryAggregator

        agg = TelemetryAggregator()
        agg.observe_metrics(
            0, 10, self._scalars(30.0, 60.0, data_stall=10.0)
        )
        rec = agg.worker_goodput(0)
        assert rec["goodput_pct"] == pytest.approx(50.0)
        assert rec["seconds"]["data_stall"] == 10.0
        assert agg.worker_goodput(99) is None

    def test_fleet_goodput_wall_weighted(self):
        from dlrover_tpu.obs.aggregate import TelemetryAggregator

        agg = TelemetryAggregator()
        assert agg.fleet_goodput() is None
        agg.observe_metrics(0, 10, self._scalars(90.0, 100.0))
        agg.observe_metrics(1, 10, self._scalars(10.0, 100.0))
        fleet = agg.fleet_goodput()
        assert fleet["goodput_pct"] == pytest.approx(50.0)
        assert fleet["workers"] == 2
        assert fleet["wall_s"] == pytest.approx(200.0)

    def test_departed_worker_leaves_fleet_number(self):
        from dlrover_tpu.obs.aggregate import TelemetryAggregator

        agg = TelemetryAggregator()
        agg.observe_metrics(0, 10, self._scalars(90.0, 100.0))
        agg.observe_metrics(1, 10, self._scalars(10.0, 100.0))
        agg.remove_worker(1)
        assert agg.fleet_goodput()["goodput_pct"] == pytest.approx(90.0)

    def test_export_publishes_and_prunes_gauges(self):
        from dlrover_tpu.obs.aggregate import TelemetryAggregator

        agg = TelemetryAggregator()
        agg.observe_metrics(0, 10, self._scalars(90.0, 100.0))
        agg.observe_metrics(1, 10, self._scalars(10.0, 100.0))
        reg = MetricsRegistry()
        agg.export(reg)
        scalars = reg.scalars()
        assert scalars["dlrover_goodput_fleet_pct"] == pytest.approx(50.0)
        assert scalars[
            'dlrover_goodput_worker_pct{worker="1"}'
        ] == pytest.approx(10.0)
        key = (
            'dlrover_goodput_fleet_seconds_total'
            '{category="productive_compute"}'
        )
        assert scalars[key] == pytest.approx(100.0)
        agg.remove_worker(1)
        agg.export(reg)
        scalars = reg.scalars()
        assert 'dlrover_goodput_worker_pct{worker="1"}' not in scalars
        assert scalars["dlrover_goodput_fleet_pct"] == pytest.approx(90.0)

    def test_malformed_goodput_keys_ignored(self):
        from dlrover_tpu.obs.aggregate import TelemetryAggregator

        agg = TelemetryAggregator()
        agg.observe_metrics(0, 10, {
            "dlrover_goodput_wall_seconds": 0.0,  # zero wall: dropped
            'dlrover_goodput_seconds_total{category="productive_compute"}':
                5.0,
        })
        agg.observe_metrics(1, 10, {
            'dlrover_goodput_seconds_total{category="not_a_category"}':
                5.0,
            "dlrover_goodput_wall_seconds": 10.0,
        })
        assert agg.worker_goodput(0) is None
        assert agg.worker_goodput(1) is None

    def test_straggler_triggers_one_profile_request_per_episode(self):
        from dlrover_tpu.obs.aggregate import TelemetryAggregator

        requested = []
        agg = TelemetryAggregator(straggler_ratio=2.0, min_samples=4)
        agg.set_profile_requester(requested.append)
        t0 = 1000.0
        for w in range(4):
            step_s = 0.3 if w == 3 else 0.1
            for i in range(8):
                agg.observe_step_report(w, i + 1, t0 + (i + 1) * step_s)
        assert agg.detect_stragglers() == [3]
        assert requested == [3]
        agg.detect_stragglers()  # still flagged: no re-request
        assert requested == [3]


class TestGoodputReachesBrain:
    def test_sample_carries_fleet_goodput(self):
        from dlrover_tpu.master.stats.collector import JobMetricCollector

        class _SM:
            completed_global_step = 5

            def running_speed(self):
                return 1.0

        class _Telemetry:
            def fleet_goodput(self):
                return {"goodput_pct": 87.5, "wall_s": 10.0,
                        "seconds": {}, "workers": 2}

        coll = JobMetricCollector(None, _SM(), telemetry=_Telemetry())
        sample = coll.collect()
        assert sample.goodput_pct == pytest.approx(87.5)

    def test_sample_defaults_without_telemetry(self):
        from dlrover_tpu.master.stats.collector import JobMetricCollector

        class _SM:
            completed_global_step = 5

            def running_speed(self):
                return 1.0

        assert JobMetricCollector(None, _SM()).collect().goodput_pct == 0.0

    def test_brain_persists_and_queries_goodput(self):
        from dlrover_tpu.brain.service import BrainServicer
        from dlrover_tpu.common import comm

        b = BrainServicer(db_path=":memory:")
        try:
            b.persist_metrics("job-g", comm.JobMetricsSample(
                timestamp=1.0, global_step=10, steps_per_sec=2.0,
                alive_nodes=4, goodput_pct=91.25,
            ))
            rows = b.job_metrics("job-g")
            assert rows[-1].goodput_pct == pytest.approx(91.25)
        finally:
            b.close()

    def test_brain_migrates_pre_goodput_store(self, tmp_path):
        """A datastore created before the goodput column existed must
        open cleanly (ALTER migration) and serve old rows as 0.0."""
        from dlrover_tpu.brain.service import BrainServicer
        from dlrover_tpu.common import comm

        db = str(tmp_path / "old.db")
        conn = sqlite3.connect(db)
        conn.execute(
            "CREATE TABLE job_metrics (job TEXT, ts REAL, "
            "global_step INTEGER, steps_per_sec REAL, "
            "alive_nodes INTEGER, total_cpu_percent REAL, "
            "total_memory_mb INTEGER)"
        )
        conn.execute(
            "INSERT INTO job_metrics VALUES "
            "('job-old', 1.0, 5, 1.0, 2, 0.0, 0)"
        )
        conn.commit()
        conn.close()
        b = BrainServicer(db_path=db)
        try:
            rows = b.job_metrics("job-old")
            assert rows[0].goodput_pct == 0.0
            b.persist_metrics("job-old", comm.JobMetricsSample(
                timestamp=2.0, global_step=6, goodput_pct=50.0,
            ))
            assert b.job_metrics("job-old")[-1].goodput_pct == 50.0
        finally:
            b.close()


class TestCardinalityGuard:
    def test_cap_refuses_growth_and_warns_once(self):
        reg = MetricsRegistry()
        g = reg.gauge("capped", "g", labelnames=("w",), max_label_sets=3)
        for i in range(3):
            g.labels(str(i)).set(float(i))
        assert g.label_set_count() == 3
        assert not g._overflow_warned
        g.labels("overflow-a").set(99.0)  # refused, warned
        g.labels("overflow-b").set(98.0)  # refused, silent
        assert g._overflow_warned
        assert g.label_set_count() == 3
        text = reg.prometheus_text()
        assert "overflow-a" not in text and "overflow-b" not in text
        assert 'capped{w="2"}' in text
        # existing label sets still writable past the cap
        g.labels("1").set(41.0)
        assert 'capped{w="1"} 41' in reg.prometheus_text()

    def test_overflow_child_is_usable_dead_end(self):
        reg = MetricsRegistry()
        c = reg.counter("cc", "c", labelnames=("w",), max_label_sets=1)
        c.labels("a").inc()
        c.labels("b").inc(5)  # overflow: works, never exported
        assert c.labels("a").value == 1.0
        assert 'cc{w="b"}' not in reg.prometheus_text()

    def test_env_configures_default_cap(self, monkeypatch):
        from dlrover_tpu.obs.metrics import ENV_MAX_LABEL_SETS

        monkeypatch.setenv(ENV_MAX_LABEL_SETS, "2")
        g = MetricsRegistry().gauge("envcap", "g", labelnames=("w",))
        assert g.max_label_sets == 2
        monkeypatch.setenv(ENV_MAX_LABEL_SETS, "not-a-number")
        g2 = MetricsRegistry().gauge("envcap2", "g", labelnames=("w",))
        assert g2.max_label_sets == 256

    def test_histogram_honors_cap(self):
        reg = MetricsRegistry()
        h = reg.histogram(
            "hh", "h", labelnames=("w",), max_label_sets=1
        )
        h.labels("a").observe(0.1)
        h.labels("b").observe(0.2)
        assert h.label_set_count() == 1


class TestMergeTimeline:
    def _trace(self, wall_t0, name="step", ts=0.0, dur=1000.0):
        return {
            "traceEvents": [{
                "ph": "X", "name": name, "ts": ts, "dur": dur,
                "pid": 1, "tid": 1, "args": {"depth": 0},
            }],
            "displayTimeUnit": "ms",
            "otherData": {"wall_t0_s": wall_t0, "pid": 123},
        }

    def test_aligns_on_shared_wall_clock(self):
        from tools.merge_timeline import merge_traces

        merged = merge_traces(
            [self._trace(100.0), self._trace(101.5)], ["w0", "w1"]
        )
        ok, reason = validate_chrome_trace(merged)
        assert ok, reason
        xs = [
            e for e in merged["traceEvents"] if e.get("ph") == "X"
        ]
        by_pid = {e["pid"]: e for e in xs}
        assert by_pid[1]["ts"] == pytest.approx(0.0)
        assert by_pid[2]["ts"] == pytest.approx(1.5e6)  # 1.5s later
        names = {
            e["pid"]: e["args"]["name"]
            for e in merged["traceEvents"]
            if e.get("name") == "process_name"
        }
        assert names[1] == "w0" and names[2] == "w1"

    def test_node_events_overlay_as_instants(self):
        from tools.merge_timeline import MASTER_PID, merge_traces

        events = [
            {"node_type": "worker", "node_id": 1, "event": "restart",
             "detail": "hang", "ts": 102.0},
            {"ts": 100.5, "kind": "ckpt_degraded", "detail": "enospc"},
        ]
        merged = merge_traces(
            [self._trace(100.0)], ["w0"], events=events
        )
        instants = [
            e for e in merged["traceEvents"] if e.get("ph") == "i"
        ]
        assert [e["name"] for e in instants] == [
            "ckpt_degraded", "restart",  # sorted by time
        ]
        assert all(e["pid"] == MASTER_PID for e in instants)
        assert instants[0]["ts"] == pytest.approx(0.5e6)
        assert instants[1]["ts"] == pytest.approx(2.0e6)
        assert instants[1]["args"]["node_id"] == 1

    def test_unanchored_trace_still_merges(self):
        from tools.merge_timeline import merge_traces

        legacy = {"traceEvents": [
            {"ph": "X", "name": "step", "ts": 5.0, "dur": 1.0,
             "pid": 9, "tid": 1},
        ]}
        merged = merge_traces(
            [self._trace(100.0), legacy], ["w0", "legacy"]
        )
        assert merged["otherData"]["unaligned"] == ["legacy"]
        legacy_evt = [
            e for e in merged["traceEvents"]
            if e.get("ph") == "X" and e["pid"] == 2
        ][0]
        assert legacy_evt["ts"] == pytest.approx(5.0)  # offset 0

    def test_empty_inputs_raise(self):
        from tools.merge_timeline import merge_traces

        with pytest.raises(ValueError):
            merge_traces([], [])

    def test_cli_round_trip(self, tmp_path):
        from tools.merge_timeline import main

        p0 = tmp_path / "w0.json"
        p1 = tmp_path / "w1.json"
        ev = tmp_path / "events.json"
        out = tmp_path / "merged.json"
        p0.write_text(json.dumps(self._trace(100.0)))
        p1.write_text(json.dumps(self._trace(103.0)))
        ev.write_text(json.dumps([
            {"ts": 101.0, "kind": "straggler", "detail": "worker 1"},
        ]))
        rc = main([
            str(p0), str(p1), "-o", str(out), "--events", str(ev),
        ])
        assert rc == 0
        with open(out) as f:
            merged = json.load(f)
        ok, reason = validate_chrome_trace(merged)
        assert ok, reason
        assert merged["otherData"]["sources"] == ["w0", "w1"]

    def test_real_tracer_dump_carries_anchor(self, tmp_path):
        """The producer side of the contract: SpanTracer.chrome_trace
        embeds the wall anchor merge_timeline aligns on."""
        before = time.time()
        tr = SpanTracer(enabled=True)
        with tr.span("compute"):
            pass
        trace = tr.chrome_trace()
        assert before <= trace["otherData"]["wall_t0_s"] <= time.time()
        from tools.merge_timeline import merge_traces

        merged = merge_traces([trace, self._trace(time.time())])
        ok, reason = validate_chrome_trace(merged)
        assert ok, reason
