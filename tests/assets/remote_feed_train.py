"""Trainer-node script for the cross-host coworker data-plane e2e:
discover the data node via the master KV store, pull batches through
the remote feeder into the local shm ring, consume, report totals."""

import os
import sys

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.data.remote_feed import (
    RemoteBatchFeeder,
    discover_data_nodes,
)
from dlrover_tpu.trainer.elastic.distributed import init_elastic


def main() -> int:
    ctx = init_elastic()
    client = MasterClient(
        ctx.master_addr, node_id=ctx.node_rank, node_type="worker"
    )
    addrs = discover_data_nodes(client, timeout=60)
    feeder = RemoteBatchFeeder(addrs, name=f"rf{os.getpid()}")
    count = 0
    total = 0
    try:
        for batch in feeder:
            count += 1
            total += int(batch["x"].sum())
    finally:
        feeder.close()
    out = os.environ["RF_OUT"]
    with open(f"{out}.{ctx.node_rank}", "w") as f:
        f.write(f"{count} {total}")
    print(f"node {ctx.node_rank}: {count} batches", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
