"""Chaos-soak worker: flash-checkpointed training that survives random
SIGKILLs of whole nodes (used by the chaos soak / LocalCluster).

Every step flash-saves to memory; the AGENT persists shm to storage at
breakpoints/SIGTERM (no blocking disk saves — see the inline comment).
A relaunched or membership-restarted worker resumes from the newest
checkpoint it can see and keeps going until CHAOS_STEPS. Exits 0 once
the target step is reached.
"""

import os
import sys
import time

import numpy as np

from dlrover_tpu.ckpt import FlashCheckpointer
from dlrover_tpu.ckpt.checkpointer import StorageType
from dlrover_tpu.trainer.elastic.distributed import init_elastic


def main() -> int:
    ctx = init_elastic()
    import jax.numpy as jnp

    world_log = os.getenv("CHAOS_WORLD_LOG", "")
    if world_log:
        # slice-unit tests assert every frozen world honored node_unit:
        # append this incarnation's (rdzv_round, node_num) observation
        with open(world_log, "a") as f:
            f.write(f"{ctx.rdzv_round} {ctx.node_num}\n")

    total = int(os.getenv("CHAOS_STEPS", "60"))
    step_secs = float(os.getenv("CHAOS_STEP_SECS", "0.2"))
    # ONE shared dir for the whole job: the commit protocol counts done
    # files from every node's saver in the same tree
    ckpt_dir = os.getenv("CHAOS_CKPT_DIR", "/tmp/dlrover_tpu/chaos_ckpt")

    ckptr = FlashCheckpointer(ckpt_dir)
    state = {"w": jnp.zeros((8,)), "step": 0}
    start, restored = ckptr.load_checkpoint(state)
    if restored is not None:
        state = restored
        print(f"node {ctx.node_rank}: resumed from step {start}", flush=True)

    for step in range(int(state["step"]) + 1, total + 1):
        state = {"w": state["w"] + 1.0, "step": step}
        time.sleep(step_secs)
        # memory saves only: the agent persists at breakpoints and the
        # engine falls back to storage on restore. A blocking DISK save
        # would be wrong here — this toy trains per-node independently
        # (no collectives), so after an asymmetric resume one node can
        # wait on a global commit whose peer shard never comes; real
        # SPMD jobs execute steps in lockstep and cannot diverge
        saved = ckptr.save_checkpoint(
            step, state, storage_type=StorageType.MEMORY
        )
        if step % 10 == 0:
            print(
                f"node {ctx.node_rank}: step {step} saved={saved}",
                flush=True,
            )

    w = float(np.asarray(state["w"])[0])
    if w != float(total):
        print(f"FAIL: w={w} want {total}", flush=True)
        return 1
    print(f"node {ctx.node_rank}: chaos_train done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
