import sys; sys.exit(3)
