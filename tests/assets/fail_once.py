"""Fails on the first launch, succeeds after one agent restart."""

import os
import sys

restart = int(os.getenv("DLROVER_TPU_RESTART_COUNT", "0"))
sys.exit(1 if restart == 0 else 0)
