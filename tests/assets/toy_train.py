"""Minimal elastic JAX training worker: distributed init + DP grad step.

Used by the end-to-end launcher test: two processes form a mesh via the
master-assigned coordinator, take one data-parallel gradient step, and
assert the cross-process psum agrees.
"""

import sys

import numpy as np

from dlrover_tpu.trainer.elastic.distributed import init_elastic


def main() -> int:
    ctx = init_elastic()
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    n = jax.device_count()

    w = jnp.zeros((4,))
    # each process contributes a distinct slice of the global batch
    local = np.full(
        (jax.local_device_count() * 2, 4),
        ctx.process_id + 1.0,
        np.float32,
    )
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local
    )

    @jax.jit
    def step(w, x):
        def loss(w):
            return jnp.mean((x @ w - 1.0) ** 2)

        g = jax.grad(loss)(w)
        return w - 0.1 * g

    w = step(w, x)
    w_local = np.asarray(jax.device_get(w))
    # grad is identical on all processes only if psum really crossed
    print(f"proc {ctx.process_id}: w={w_local}", flush=True)
    expected_mean_x = (1.0 + 2.0) / 2 if ctx.num_processes == 2 else 1.0
    got = w_local[0]
    want = 0.1 * 2 * expected_mean_x  # -lr * dL/dw at w=0: 2*mean(x*(x@w-1))
    if abs(got - want) > 1e-4:
        print(f"MISMATCH: got {got}, want {want}", flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
