"""Elastic worker exercising Flash Checkpoint through the real agent.

Run 0: trains to step 3, flash-saves each step to memory only, then dies
hard (simulated preemption) — the agent's save-at-breakpoint must persist
the shm checkpoint before restarting us.
Run 1: must resume from step 3 and finish.
"""

import os
import sys
import time

import numpy as np

from dlrover_tpu.trainer.elastic.distributed import init_elastic
from dlrover_tpu.ckpt import FlashCheckpointer


def main() -> int:
    init_elastic()
    import jax.numpy as jnp

    ckpt_dir = os.environ["TEST_CKPT_DIR"]
    restart = int(os.getenv("DLROVER_TPU_RESTART_COUNT", "0"))

    ckptr = FlashCheckpointer(ckpt_dir)
    state = {"w": jnp.zeros((8,)), "step": 0}
    start, restored = ckptr.load_checkpoint(state)
    if restored is not None:
        state = restored
        print(f"resumed from step {start}", flush=True)

    if restart > 0 and int(state["step"]) < 3:
        print(f"FAIL: resumed at step {state['step']}, want 3", flush=True)
        return 1

    for step in range(int(state["step"]) + 1, 6):
        state = {"w": state["w"] + 1.0, "step": step}
        # a memory save is skipped (not blocked) while the saver is busy;
        # retry so every step really lands in shm before we move on
        for _ in range(100):
            if ckptr.save_checkpoint(step, state):
                break
            time.sleep(0.2)
        if restart == 0 and step == 3:
            # die without persisting to disk: only shm has step 3
            os._exit(13)

    w = np.asarray(state["w"])
    if not np.allclose(w, 5.0):
        print(f"FAIL: w={w}", flush=True)
        return 1
    print("ckpt_train done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
