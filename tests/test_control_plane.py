"""Control-plane scale-out tests (ISSUE 14 tentpole a): the delta
telemetry codec, the batched AgentReportBatch dispatch, the agent
aggregation-tier daemon, channel hardening (keepalive + gzip), the
client-side RPC brownout counters, and the rpc_load harness."""

import json
import os
import sys
import time

import grpc
import numpy as np
import pytest

from dlrover_tpu.agent.aggregator import AgentReportBatcher
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.monitor import (
    read_worker_commands,
    report_runtime_metrics,
)
from dlrover_tpu.common import comm
from dlrover_tpu.common.telemetry_delta import DeltaDecoder, DeltaEncoder
from dlrover_tpu.master.servicer import MasterServicer, create_master_service

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)


# ---------------------------------------------------------------------------
# delta codec
# ---------------------------------------------------------------------------
class TestDeltaCodec:
    def test_full_then_delta_roundtrip(self):
        enc = DeltaEncoder()
        dec = DeltaDecoder()
        s1 = {"a": 1.0, "b": 2.0}
        full, seq, d = enc.encode({0: s1})
        assert full and seq == 1
        out = dec.apply(7, enc.epoch, seq, full, d)
        assert out == {0: s1}
        enc.ack(seq)
        # change one key, add one, remove one
        s2 = {"a": 1.5, "c": 3.0}
        full, seq, d = enc.encode({0: s2})
        assert not full
        changed, removed = d[0]
        assert changed == {"a": 1.5, "c": 3.0}
        assert removed == ["b"]
        out = dec.apply(7, enc.epoch, seq, full, d)
        assert out == {0: s2}
        assert dec.snapshot(7) == {0: s2}

    def test_unchanged_keys_not_resent(self):
        enc = DeltaEncoder()
        snap = {f"k{i}": float(i) for i in range(50)}
        _, seq, _ = enc.encode({0: snap})
        enc.ack(seq)
        snap2 = dict(snap, k3=99.0)
        full, seq, d = enc.encode({0: snap2})
        assert not full
        assert d[0][0] == {"k3": 99.0}  # ONLY the changed key
        # no change at all → no entry for the proc
        enc.ack(seq)
        full, seq, d = enc.encode({0: snap2})
        assert d == {}

    def test_rollback_arms_full_snapshot(self):
        """A transport failure makes the next batch a full snapshot:
        whether or not the master applied the lost batch, a snapshot
        converges (re-encoding a delta could diverge)."""
        enc = DeltaEncoder()
        _, seq, _ = enc.encode({0: {"a": 1.0}})
        enc.ack(seq)
        _, seq, d = enc.encode({0: {"a": 2.0}})
        enc.rollback(seq)  # send failed
        full, seq2, d2 = enc.encode({0: {"a": 2.0, "b": 1.0}})
        assert full  # snapshot, not a recomputed delta
        assert d2[0][0] == {"a": 2.0, "b": 1.0}

    def test_rollback_converges_when_value_reverts(self):
        """The divergence the full-snapshot recovery exists for: the
        master APPLIED the lost batch, and the changed key reverted to
        its acked value before the resend. A recomputed delta would
        omit the key and strand the master at the stale value; the
        snapshot overwrites it."""
        enc = DeltaEncoder()
        dec = DeltaDecoder()
        full, seq, d = enc.encode({0: {"gauge": 0.0}})
        dec.apply(1, enc.epoch, seq, full, d)
        enc.ack(seq)
        # gauge flips to 1; master applies it but the response is lost
        full, seq, d = enc.encode({0: {"gauge": 1.0}})
        dec.apply(1, enc.epoch, seq, full, d)
        enc.rollback(seq)
        # gauge reverts to 0 before the retry
        full, seq, d = enc.encode({0: {"gauge": 0.0}})
        out = dec.apply(1, enc.epoch, seq, full, d)
        assert out == {0: {"gauge": 0.0}}  # master converged
        assert dec.snapshot(1) == {0: {"gauge": 0.0}}

    def test_same_seq_replay_is_idempotent(self):
        """A lost RESPONSE: the master applied seq N, the client
        resends N — the decoder re-applies without resync."""
        enc = DeltaEncoder()
        dec = DeltaDecoder()
        full, seq, d = enc.encode({0: {"a": 1.0}})
        dec.apply(1, enc.epoch, seq, full, d)
        enc.ack(seq)
        full, seq, d = enc.encode({0: {"a": 2.0}})
        assert dec.apply(1, enc.epoch, seq, full, d) == {0: {"a": 2.0}}
        # replay (response lost, client resent the same seq)
        assert dec.apply(1, enc.epoch, seq, full, d) == {0: {"a": 2.0}}
        assert dec.replays == 1
        assert dec.resyncs == 0

    def test_epoch_mismatch_and_gap_force_resync(self):
        dec = DeltaDecoder()
        enc = DeltaEncoder()
        full, seq, d = enc.encode({0: {"a": 1.0}})
        dec.apply(1, enc.epoch, seq, full, d)
        enc.ack(seq)
        # wrong epoch
        assert dec.apply(1, enc.epoch + 1, 2, False, {0: ({"a": 2.0}, [])}) is None
        # seq gap
        assert dec.apply(1, enc.epoch, 5, False, {0: ({"a": 2.0}, [])}) is None
        # unknown node
        assert dec.apply(9, enc.epoch, 2, False, {0: ({}, [])}) is None
        assert dec.resyncs == 3
        # resync converges: fresh epoch, full snapshot
        enc.force_resync()
        full, seq, d = enc.encode({0: {"a": 2.0}})
        assert full and seq == 1
        assert dec.apply(1, enc.epoch, seq, full, d) == {0: {"a": 2.0}}

    def test_vanished_proc_removes_all_keys(self):
        enc = DeltaEncoder()
        dec = DeltaDecoder()
        full, seq, d = enc.encode({0: {"a": 1.0}, 1: {"b": 2.0}})
        dec.apply(1, enc.epoch, seq, full, d)
        enc.ack(seq)
        full, seq, d = enc.encode({0: {"a": 1.0}})  # proc 1 gone
        assert d[1] == ({}, ["b"])
        out = dec.apply(1, enc.epoch, seq, full, d)
        assert out[1] == {}
        assert dec.snapshot(1) == {0: {"a": 1.0}}  # no ghost scalars

    def test_fresh_epochs_differ(self):
        assert DeltaEncoder().epoch != DeltaEncoder().epoch


# ---------------------------------------------------------------------------
# comm serialization round trips (every new message)
# ---------------------------------------------------------------------------
class TestCommRoundTrip:
    @pytest.mark.parametrize(
        "msg",
        [
            comm.ProcDelta(
                proc_id=2,
                worker_id=5,
                step=42,
                step_ts=1.5,
                step_advanced=True,
                changed={"loss": 0.5, 'g{c="x"}': 1.0},
                removed=["stale"],
                open_span="ckpt_commit",
                open_span_elapsed_s=3.25,
            ),
            comm.AgentReportBatch(
                node_id=3,
                epoch=12345,
                seq=7,
                full=True,
                procs=[comm.ProcDelta(proc_id=0, changed={"a": 1.0})],
                command_ack_id=9,
                paral_version=2,
                resource=comm.ResourceStats(
                    node_id=3, cpu_percent=51.0, used_memory_mb=2048
                ),
            ),
            comm.AgentBatchResponse(
                resync=True,
                commands=[
                    comm.WorkerCommand(id=1, kind="flight_dump", arg=3)
                ],
                paral_config=comm.ParallelConfig(),
            ),
        ],
        ids=lambda m: type(m).__name__,
    )
    def test_roundtrip(self, msg):
        assert comm.deserialize_message(comm.serialize_message(msg)) == msg


# ---------------------------------------------------------------------------
# servicer dispatch
# ---------------------------------------------------------------------------
class _Collector:
    def __init__(self):
        self.metrics = {}
        self.calls = 0

    def report_train_metrics(self, worker_id, step, metrics):
        self.metrics[worker_id] = (step, dict(metrics))
        self.calls += 1


class _Speed:
    def __init__(self):
        self.steps = []

    def collect_global_step(self, step, ts=None, node_id=0):
        self.steps.append((node_id, step, ts))


class _Telemetry:
    def __init__(self):
        self.observed = []

    def observe_metrics(
        self, worker_id, step, metrics, open_span="",
        open_span_elapsed_s=0.0,
    ):
        self.observed.append(
            (worker_id, step, dict(metrics), open_span)
        )


class _ParalService:
    def __init__(self, version=3):
        self.cfg = comm.ParallelConfig()
        self.cfg.dataloader.version = version
        self.cfg.dataloader.batch_size = 32

    def get_config(self, node_id):
        return self.cfg


def _dispatch(servicer, message, node_id=3, rpc="report"):
    req = comm.serialize_message(
        comm.BaseRequest(
            node_id=node_id,
            node_type="worker",
            data=comm.serialize_message(message),
        )
    )
    fn = servicer.report if rpc == "report" else servicer.get
    resp = comm.deserialize_message(fn(req))
    assert resp.success, resp.message
    return comm.deserialize_message(resp.data)


class TestServicerBatchDispatch:
    def _servicer(self, paral=None):
        self.collector = _Collector()
        self.speed = _Speed()
        self.telemetry = _Telemetry()
        return MasterServicer(
            metric_collector=self.collector,
            speed_monitor=self.speed,
            telemetry=self.telemetry,
            paral_config_service=paral,
        )

    def _batch(self, enc, scalars, step=10, advanced=True, node_id=3):
        full, seq, d = enc.encode({0: scalars})
        changed, removed = d.get(0, ({}, []))
        return comm.AgentReportBatch(
            node_id=node_id,
            epoch=enc.epoch,
            seq=seq,
            full=full,
            procs=[
                comm.ProcDelta(
                    proc_id=0,
                    step=step,
                    step_ts=float(step),
                    step_advanced=advanced,
                    changed=changed,
                    removed=removed,
                    open_span="compute",
                )
            ],
        )

    def test_batch_forwards_reconstructed_full_scalars(self):
        s = self._servicer()
        enc = DeltaEncoder()
        scalars = {"loss": 1.0, "lr": 0.1}
        resp = _dispatch(s, self._batch(enc, scalars))
        assert isinstance(resp, comm.AgentBatchResponse)
        assert not resp.resync
        enc.ack(enc.seq)
        assert self.collector.metrics[3] == (10, scalars)
        assert self.speed.steps == [(3, 10, 10.0)]
        # delta tick: master forwards the FULL reconstruction
        scalars2 = dict(scalars, loss=0.9)
        resp = _dispatch(s, self._batch(enc, scalars2, step=11))
        assert not resp.resync
        assert self.collector.metrics[3] == (11, scalars2)
        assert self.telemetry.observed[-1][2] == scalars2
        assert self.telemetry.observed[-1][3] == "compute"

    def test_step_advanced_gates_speed_monitor(self):
        s = self._servicer()
        enc = DeltaEncoder()
        _dispatch(s, self._batch(enc, {"a": 1.0}, step=5))
        enc.ack(enc.seq)
        n = len(self.speed.steps)
        _dispatch(
            s, self._batch(enc, {"a": 2.0}, step=5, advanced=False)
        )
        assert len(self.speed.steps) == n  # no re-report at same step

    def test_epoch_mismatch_forces_resync_and_converges(self):
        """The mixed-version/failover drill: a delta the master cannot
        reconstruct applies NOTHING, answers resync, and the client's
        full snapshot converges with no dropped scalars."""
        s = self._servicer()
        enc = DeltaEncoder()
        _dispatch(s, self._batch(enc, {"a": 1.0, "b": 2.0}))
        enc.ack(enc.seq)
        # master restarts: fresh decoder
        s._delta = DeltaDecoder()
        before = dict(self.collector.metrics[3][1])
        scalars = {"a": 1.5, "b": 2.0, "c": 3.0}
        resp = _dispatch(s, self._batch(enc, scalars, step=11))
        assert resp.resync
        # nothing applied from the unreconstructable delta
        assert self.collector.metrics[3][1] == before
        # client resyncs: full snapshot under a fresh epoch
        enc.force_resync()
        resp = _dispatch(s, self._batch(enc, scalars, step=11))
        assert not resp.resync
        assert self.collector.metrics[3] == (11, scalars)

    def test_old_format_reports_still_dispatch(self):
        """Mixed-version fleet: a legacy (non-batched, non-delta)
        client's reports hit the same sinks with full fidelity."""
        s = self._servicer()
        _dispatch(
            s,
            comm.TrainMetricsReport(
                node_id=4, step=7, metrics={"loss": 2.0}
            ),
            node_id=4,
        )
        _dispatch(
            s,
            comm.GlobalStepReport(node_id=4, step=7, timestamp=1.0),
            node_id=4,
        )
        assert self.collector.metrics[4] == (7, {"loss": 2.0})
        assert (4, 7, 1.0) in self.speed.steps
        # and a batched node coexists
        enc = DeltaEncoder()
        _dispatch(s, self._batch(enc, {"loss": 1.0}, node_id=5), node_id=5)
        assert self.collector.metrics[5] == (10, {"loss": 1.0})

    def test_command_leg_piggybacks_and_acks(self):
        s = self._servicer()
        enc = DeltaEncoder()
        cmd = s.queue_worker_command(3, "flight_dump", reason="test")
        resp = _dispatch(s, self._batch(enc, {"a": 1.0}))
        enc.ack(enc.seq)
        assert [c.id for c in resp.commands] == [cmd.id]
        # unacked → redelivered on the next batch
        b = self._batch(enc, {"a": 2.0})
        b.command_ack_id = 0
        resp = _dispatch(s, b)
        enc.ack(enc.seq)
        assert [c.id for c in resp.commands] == [cmd.id]
        # acked → cleared
        b = self._batch(enc, {"a": 3.0})
        b.command_ack_id = cmd.id
        resp = _dispatch(s, b)
        assert resp.commands == []
        assert 3 not in s._worker_commands

    def test_paral_config_leg_only_on_version_change(self):
        s = self._servicer(paral=_ParalService(version=3))
        enc = DeltaEncoder()
        b = self._batch(enc, {"a": 1.0})
        b.paral_version = 0  # stale
        resp = _dispatch(s, b)
        enc.ack(enc.seq)
        assert resp.paral_config is not None
        assert resp.paral_config.dataloader.version == 3
        b = self._batch(enc, {"a": 2.0})
        b.paral_version = 3  # current
        resp = _dispatch(s, b)
        assert resp.paral_config is None

    def test_resource_leg_forwards_to_job_manager(self):
        class _JM:
            def __init__(self):
                self.usage = None

            def update_node_resource_usage(self, t, nid, cpu, mem):
                self.usage = (t, nid, cpu, mem)

        jm = _JM()
        s = MasterServicer(job_manager=jm)
        enc = DeltaEncoder()
        full, seq, d = enc.encode({0: {}})
        b = comm.AgentReportBatch(
            node_id=3, epoch=enc.epoch, seq=seq, full=full,
            resource=comm.ResourceStats(
                node_id=3, cpu_percent=77.0, used_memory_mb=512
            ),
        )
        _dispatch(s, b)
        assert jm.usage == ("worker", 3, 77.0, 512)

    def test_rpc_metrics_recorded_per_message_type(self):
        s = self._servicer()
        _dispatch(
            s, comm.GlobalStepReport(node_id=1, step=1, timestamp=1.0)
        )
        c = s._rpc_obs.requests.labels("report", "GlobalStepReport")
        assert c.value >= 1
        h = s._rpc_obs.latency.labels("report", "GlobalStepReport")
        assert h.count >= 1 and h.sum > 0
        b = s._rpc_obs.bytes.labels("report", "GlobalStepReport", "in")
        assert b.value > 0


# ---------------------------------------------------------------------------
# agent aggregation tier (the batcher daemon)
# ---------------------------------------------------------------------------
class _LoopbackClient:
    """MasterClient stand-in that dispatches straight into a servicer
    (no gRPC): the batcher's protocol behavior, isolated."""

    def __init__(self, servicer, node_id=3):
        self._servicer = servicer
        self.node_id = node_id
        self.eviction_notices = []
        self.fail_next = 0

    def report_batch(self, batch):
        if self.fail_next > 0:
            self.fail_next -= 1
            raise ConnectionError("injected transport failure")
        resp = _dispatch(self._servicer, batch, node_id=self.node_id)
        return (
            resp
            if isinstance(resp, comm.AgentBatchResponse)
            else comm.AgentBatchResponse()
        )

    def report_eviction_notice(self, grace_s, drain_ms=0.0, reason=""):
        self.eviction_notices.append((grace_s, drain_ms, reason))


class TestAgentReportBatcher:
    def _setup(self, tmp_path, paral=None):
        self.collector = _Collector()
        self.speed = _Speed()
        self.telemetry = _Telemetry()
        self.servicer = MasterServicer(
            metric_collector=self.collector,
            speed_monitor=self.speed,
            telemetry=self.telemetry,
            paral_config_service=paral,
        )
        self.client = _LoopbackClient(self.servicer)
        self.mpath = str(tmp_path / "metrics.json")
        self.cpath = str(tmp_path / "commands.json")
        self.ppath = str(tmp_path / "paral.json")
        return AgentReportBatcher(
            self.client,
            procs=[(0, -1, self.mpath)],
            commands_path=self.cpath,
            paral_path=self.ppath,
        )

    def test_one_rpc_per_tick_with_delta(self, tmp_path):
        b = self._setup(tmp_path)
        report_runtime_metrics(5, path=self.mpath, loss=2.0, lr=0.1)
        b._tick()
        assert b.batches_sent == 1
        assert self.collector.metrics[3][1] == {"loss": 2.0, "lr": 0.1}
        assert self.speed.steps[-1][:2] == (3, 5)
        full_bytes = b.last_wire_bytes
        # one scalar changes: the delta tick is strictly smaller
        report_runtime_metrics(6, path=self.mpath, loss=1.5, lr=0.1)
        b._tick()
        assert b.batches_sent == 2
        assert b.last_wire_bytes < full_bytes
        assert self.collector.metrics[3][1] == {"loss": 1.5, "lr": 0.1}
        assert self.speed.steps[-1][:2] == (3, 6)
        # quiet tick: the batch still goes out (it IS the poll leg)
        # with no proc entries
        b._tick()
        assert b.batches_sent == 3
        assert self.collector.metrics[3][1] == {"loss": 1.5, "lr": 0.1}

    def test_resync_after_master_restart_converges(self, tmp_path):
        b = self._setup(tmp_path)
        report_runtime_metrics(5, path=self.mpath, loss=2.0)
        b._tick()
        self.servicer._delta = DeltaDecoder()  # master restart
        report_runtime_metrics(6, path=self.mpath, loss=1.0, acc=0.5)
        b._tick()  # delta rejected → resync armed
        assert b.resyncs == 1
        b._tick()  # full snapshot converges, even with no new advance
        assert self.collector.metrics[3][1] == {"loss": 1.0, "acc": 0.5}

    def test_transport_failure_rolls_back_and_resends(self, tmp_path):
        b = self._setup(tmp_path)
        report_runtime_metrics(5, path=self.mpath, loss=2.0)
        b._tick()
        report_runtime_metrics(6, path=self.mpath, loss=1.0)
        self.client.fail_next = 1
        b._tick()  # lost request: rolled back, nothing dropped
        b._tick()
        assert self.collector.metrics[3][1] == {"loss": 1.0}
        assert self.servicer._delta.resyncs == 0  # no gap, no resync

    def test_commands_ride_the_batch_into_the_file(self, tmp_path):
        b = self._setup(tmp_path)
        cmd = self.servicer.queue_worker_command(
            3, "profile", arg=12, reason="straggler"
        )
        report_runtime_metrics(5, path=self.mpath, loss=2.0)
        b._tick()
        cmds = read_worker_commands(self.cpath)
        assert [c["id"] for c in cmds] == [cmd.id]
        assert cmds[0]["kind"] == "profile" and cmds[0]["arg"] == 12
        # the ack watermark cleared it master-side on the next tick
        b._tick()
        assert 3 not in self.servicer._worker_commands

    def test_paral_config_rides_the_batch_into_the_file(self, tmp_path):
        """The batcher's DEFAULT paral_version (-1, 'I have nothing')
        must receive the config on its first tick — the legacy tuner's
        initial-write parity (regression: a -1 sentinel the servicer
        read as 'does not want' made the channel permanently dead)."""
        b = self._setup(tmp_path, paral=_ParalService(version=4))
        assert b._paral_version == -1
        report_runtime_metrics(5, path=self.mpath, loss=2.0)
        b._tick()
        with open(self.ppath) as f:
            cfg = json.load(f)
        assert cfg["dataloader"]["version"] == 4
        assert b._paral_version == 4

    def test_eviction_relayed_first_on_dedicated_rpc(self, tmp_path):
        b = self._setup(tmp_path)
        report_runtime_metrics(
            5, path=self.mpath, loss=2.0,
            eviction_pending=1.0, eviction_grace_s=30.0,
        )
        b._tick()
        assert self.client.eviction_notices == [(30.0, 0.0, "worker_drain")]
        b._tick()  # unchanged notice: not re-sent
        assert len(self.client.eviction_notices) == 1

    def test_eviction_memo_is_per_proc(self, tmp_path):
        """Two draining procs with different drain values must each be
        relayed ONCE — a shared memo would thrash and re-send both
        every tick."""
        servicer = MasterServicer()
        client = _LoopbackClient(servicer, node_id=2)
        p0 = str(tmp_path / "m0.json")
        p1 = str(tmp_path / "m1.json")
        b = AgentReportBatcher(
            client,
            procs=[(0, 20, p0), (1, 21, p1)],
            commands_path=str(tmp_path / "c.json"),
            paral_path=str(tmp_path / "p.json"),
        )
        for path, drain in ((p0, 120.0), (p1, 95.0)):
            report_runtime_metrics(
                5, path=path, eviction_pending=1.0,
                eviction_grace_s=30.0, eviction_drain_ms=drain,
            )
        b._tick()
        assert sorted(n[1] for n in client.eviction_notices) == [
            95.0, 120.0,
        ]
        b._tick()  # unchanged: nothing re-sent
        b._tick()
        assert len(client.eviction_notices) == 2

    def test_multi_proc_batch_attributes_per_worker(self, tmp_path):
        self.collector = _Collector()
        self.speed = _Speed()
        servicer = MasterServicer(
            metric_collector=self.collector, speed_monitor=self.speed
        )
        client = _LoopbackClient(servicer, node_id=2)
        p0 = str(tmp_path / "m0.json")
        p1 = str(tmp_path / "m1.json")
        b = AgentReportBatcher(
            client,
            procs=[(0, 20, p0), (1, 21, p1)],
            commands_path=str(tmp_path / "c.json"),
            paral_path=str(tmp_path / "p.json"),
        )
        report_runtime_metrics(5, path=p0, loss=1.0)
        report_runtime_metrics(7, path=p1, loss=3.0)
        b._tick()
        assert b.batches_sent == 1  # ONE rpc for both procs
        assert self.collector.metrics[20] == (5, {"loss": 1.0})
        assert self.collector.metrics[21] == (7, {"loss": 3.0})
        assert {(n, s) for n, s, _ in self.speed.steps} == {
            (20, 5), (21, 7),
        }


# ---------------------------------------------------------------------------
# channel hardening + client metrics (satellites)
# ---------------------------------------------------------------------------
class TestChannelHardening:
    def test_keepalive_options_present(self):
        opts = dict(MasterClient.KEEPALIVE_OPTIONS)
        assert opts["grpc.keepalive_time_ms"] > 0
        assert opts["grpc.keepalive_timeout_ms"] > 0
        assert opts["grpc.keepalive_permit_without_calls"] == 1

    def test_compression_flag(self):
        c = MasterClient("127.0.0.1:1", compression=True)
        assert c._compression == grpc.Compression.Gzip
        c.close()
        c = MasterClient("127.0.0.1:1", compression=False)
        assert c._compression == grpc.Compression.NoCompression
        c.close()

    def test_compression_env_default(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RPC_COMPRESSION", "0")
        c = MasterClient("127.0.0.1:1")
        assert c._compression == grpc.Compression.NoCompression
        c.close()
        monkeypatch.delenv("DLROVER_TPU_RPC_COMPRESSION")
        c = MasterClient("127.0.0.1:1")
        assert c._compression == grpc.Compression.Gzip
        c.close()

    def test_large_telemetry_payload_roundtrips_compressed(self):
        """A big, compressible telemetry payload through a REAL gRPC
        channel with gzip on: the master receives every value intact
        (and the servicer's byte counters see the uncompressed payload
        — compression is transport-level)."""
        collector = _Collector()
        servicer = MasterServicer(metric_collector=collector)
        port = comm.find_free_port()
        server = create_master_service(port, servicer)
        client = MasterClient(
            f"127.0.0.1:{port}", node_id=1, compression=True
        )
        try:
            rng = np.random.default_rng(0)
            big = {
                f"dlrover_goodput_seconds{{category=\"cat_{i}\"}}":
                float(rng.random())
                for i in range(3000)
            }
            client.report_train_metrics(9, big)
            assert collector.metrics[1] == (9, big)
        finally:
            client.close()
            server.stop(grace=None)


class TestClientRpcMetrics:
    def test_unreachable_master_counts_attempts(self):
        from dlrover_tpu.agent.master_client import _ClientRpcObs

        obs = _ClientRpcObs.get()
        req0 = obs.requests.labels("GlobalStepReport").value
        retry0 = obs.retries.labels("GlobalStepReport").value
        unreach0 = obs.unreachable.labels("GlobalStepReport").value
        client = MasterClient("127.0.0.1:1", node_id=1, timeout=0.2)
        with pytest.raises(ConnectionError):
            client._call(
                client._report_rpc,
                comm.GlobalStepReport(node_id=1, step=1),
                retries=3,
                rpc_timeout=0.2,
                retry_budget_s=5.0,
            )
        client.close()
        assert obs.requests.labels("GlobalStepReport").value == req0 + 3
        assert obs.retries.labels("GlobalStepReport").value == retry0 + 2
        assert (
            obs.unreachable.labels("GlobalStepReport").value
            == unreach0 + 1
        )

    def test_bytes_counted_on_success(self):
        from dlrover_tpu.agent.master_client import _ClientRpcObs

        obs = _ClientRpcObs.get()
        out0 = obs.bytes.labels("out").value
        in0 = obs.bytes.labels("in").value
        servicer = MasterServicer()
        port = comm.find_free_port()
        server = create_master_service(port, servicer)
        client = MasterClient(f"127.0.0.1:{port}", node_id=1)
        try:
            client.report_global_step(3)
            assert obs.bytes.labels("out").value > out0
            assert obs.bytes.labels("in").value > in0
        finally:
            client.close()
            server.stop(grace=None)

    def test_brownout_counters_reach_flight_bundle_export(self):
        """The satellite's point: the counters live in the default
        registry, so the flight recorder's metrics.prom carries them."""
        from dlrover_tpu.obs.metrics import default_registry

        client = MasterClient("127.0.0.1:1", node_id=1, timeout=0.2)
        with pytest.raises(ConnectionError):
            client.report_global_step(1, )
        client.close()
        text = default_registry().prometheus_text()
        assert "dlrover_rpc_client_requests_total" in text
        assert "dlrover_rpc_client_unreachable_total" in text


# ---------------------------------------------------------------------------
# the load harness (small fleet; 1k runs in bench --smoke, 10k is slow)
# ---------------------------------------------------------------------------
class TestRpcLoadHarness:
    def test_delta_fleet_steady_state(self):
        from rpc_load import run_load

        r = run_load(nodes=24, ticks=4, nscalars=40, churn=0.1,
                     mode="delta", pool=8)
        assert r["rpcs_per_node_per_tick"] == 1.0
        assert r["reconstructed_ok"], r
        assert r["resyncs"] == 0
        assert r["rpc_p99_ms"] > 0
        assert r["master_service_s_per_tick"] > 0

    def test_delta_beats_full_on_wire(self):
        from rpc_load import run_load

        kw = dict(nodes=16, ticks=6, nscalars=60, churn=0.1, pool=8)
        delta = run_load(mode="delta", **kw)
        full = run_load(mode="full", **kw)
        assert delta["reconstructed_ok"] and full["reconstructed_ok"]
        ratio = delta["wire_bytes_total"] / full["wire_bytes_total"]
        assert ratio < 0.6  # bench gates 0.4 at the 1k-node shape
        assert (
            delta["wire_bytes_steady_per_node_per_tick"]
            < full["wire_bytes_steady_per_node_per_tick"] * 0.4
        )

    def test_master_restart_drill_converges(self):
        from rpc_load import run_load

        r = run_load(nodes=16, ticks=4, nscalars=40, churn=0.1,
                     mode="delta", pool=8, master_restart_tick=2)
        assert r["resyncs"] == 16  # every node resynced exactly once
        assert r["reconstructed_ok"], r
        assert r["rpcs_per_node_per_tick"] <= 1.25

    def test_legacy_mode_measures_the_old_protocol(self):
        from rpc_load import run_load

        r = run_load(nodes=8, ticks=2, nscalars=20, churn=0.1,
                     mode="legacy", pool=8)
        assert r["rpcs_per_node_per_tick"] == 4.0
        assert r["reconstructed_ok"]

    @pytest.mark.slow
    def test_ten_k_fleet(self):
        """The 10k-worker tier: steady state must hold at scale."""
        from rpc_load import run_load

        r = run_load(nodes=10_000, ticks=2, nscalars=40, churn=0.1,
                     mode="delta", pool=32, verify_sample=64)
        assert r["rpcs_per_node_per_tick"] == 1.0
        assert r["reconstructed_ok"], r
