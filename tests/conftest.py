"""Test env: run JAX on a virtual 8-device CPU mesh so multi-chip sharding
logic is exercised without TPU hardware (SURVEY.md §4 lesson).

NOTE: this container's sitecustomize imports jax at interpreter start and
pins JAX_PLATFORMS=axon, so env vars are too late — only
``jax.config.update`` works (see dlrover_tpu/utils/device.py).
"""

import os

os.environ.setdefault("DLROVER_TPU_SOCKET_DIR", "/tmp/dlrover_tpu_test/sockets")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_cpu_collectives_implementation", "gloo")
