"""Test env: run JAX on a virtual 8-device CPU mesh so multi-chip sharding
logic is exercised without TPU hardware (SURVEY.md §4 lesson).

NOTE: this container's sitecustomize imports jax at interpreter start and
pins JAX_PLATFORMS=axon, so env vars are too late — only
``jax.config.update`` works (see dlrover_tpu/utils/device.py).
"""

import os

os.environ.setdefault("DLROVER_TPU_SOCKET_DIR", "/tmp/dlrover_tpu_test/sockets")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import pytest  # noqa: E402

# -- fast tier (VERDICT r4 #9) ---------------------------------------------
# `pytest -m fast` proves the core in ~2 minutes on one CPU: protocol /
# IPC, flash checkpoint, the whole control plane, the data planes, and
# ONE numerics-parity test per parallelism scheme. Compile-heavy parity
# sweeps and multi-process soaks stay in the full suite / slow tier.
_FAST_FILES = {
    "test_common.py",
    "test_master.py",
    "test_flash_checkpoint.py",
    "test_incremental_ckpt.py",
    "test_k8s.py",
    "test_brain.py",
    "test_elastic_agent.py",
    "test_monitors.py",
    "test_elastic_data.py",
    "test_autoscale.py",
    "test_master_failover.py",
    "test_remote_feed.py",
    "test_shm_feed.py",
}
_FAST_IDS = (
    # one parity test per parallelism: dp/fsdp/tp mesh, ring SP,
    # Ulysses SP, expert parallel, pipeline
    "TestModelParallelism::test_forward_invariant_to_mesh",
    "TestRingAttention::test_matches_dense",
    "TestUlyssesAttention::test_matches_dense",
    "TestMoE::test_expert_parallel_matches_dense_top1",
    "test_pipeline_forward_matches_plain",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        if "slow" in item.keywords:
            continue
        name = os.path.basename(str(item.fspath))
        if name in _FAST_FILES or any(
            fid in item.nodeid for fid in _FAST_IDS
        ):
            item.add_marker(pytest.mark.fast)
