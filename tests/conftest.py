"""Test env: run JAX on a virtual 8-device CPU mesh so multi-chip sharding
logic is exercised without TPU hardware (SURVEY.md §4 lesson).

NOTE: this container's sitecustomize imports jax at interpreter start and
pins JAX_PLATFORMS=axon, so env vars are too late — only
``jax.config.update`` works (see dlrover_tpu/utils/device.py).
"""

import os

os.environ.setdefault("DLROVER_TPU_SOCKET_DIR", "/tmp/dlrover_tpu_test/sockets")

import jax  # noqa: E402

from dlrover_tpu.common.jax_compat import (  # noqa: E402
    set_cpu_collectives,
    set_cpu_device_count,
)

jax.config.update("jax_platforms", "cpu")
# version-portable (jax_num_cpu_devices on modern jax, the XLA flag on
# 0.4.x — honored because backend creation is lazy even though
# sitecustomize already imported jax); gloo degrades to plain when the
# jaxlib wants a distributed client for it
set_cpu_device_count(8)
set_cpu_collectives("gloo")
jax.devices()

import pytest  # noqa: E402

# -- fast tier (VERDICT r4 #9) ---------------------------------------------
# `pytest -m fast` proves the core in ~2 minutes on one CPU: protocol /
# IPC, flash checkpoint, the whole control plane, the data planes, and
# ONE numerics-parity test per parallelism scheme. Compile-heavy parity
# sweeps and multi-process soaks stay in the full suite / slow tier.
_FAST_FILES = {
    "test_common.py",
    "test_master.py",
    "test_flash_checkpoint.py",
    "test_incremental_ckpt.py",
    "test_k8s.py",
    "test_brain.py",
    "test_elastic_agent.py",
    "test_monitors.py",
    "test_elastic_data.py",
    "test_autoscale.py",
    "test_master_failover.py",
    "test_remote_feed.py",
    "test_shm_feed.py",
}
_FAST_IDS = (
    # one parity test per parallelism: dp/fsdp/tp mesh, ring SP,
    # Ulysses SP, expert parallel, pipeline
    "TestModelParallelism::test_forward_invariant_to_mesh",
    "TestRingAttention::test_matches_dense",
    "TestUlyssesAttention::test_matches_dense",
    "TestMoE::test_expert_parallel_matches_dense_top1",
    "test_pipeline_forward_matches_plain",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        if "slow" in item.keywords:
            continue
        name = os.path.basename(str(item.fspath))
        if name in _FAST_FILES or any(
            fid in item.nodeid for fid in _FAST_IDS
        ):
            item.add_marker(pytest.mark.fast)
