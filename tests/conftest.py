"""Test env: run JAX on a virtual 8-device CPU mesh so multi-chip sharding
logic is exercised without TPU hardware (SURVEY.md §4 lesson)."""

import os

# Must be set before jax is imported anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("DLROVER_TPU_SOCKET_DIR", "/tmp/dlrover_tpu_test/sockets")
