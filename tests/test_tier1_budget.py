"""tools/tier1_budget.py — the pre-PR suite-budget gate (ISSUE 8
satellite: the tier-1 suite tipped over its 870s timeout twice and was
trimmed reactively both times)."""

import io
import os
import sys

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools"),
)

import tier1_budget  # noqa: E402


def _log(total="512.34s", durations=()):
    lines = [f"{d}s {kind} {tid}" for d, kind, tid in durations]
    lines.append(f"=========== 562 passed, 3 skipped in {total} ======")
    return "\n".join(lines) + "\n"


class TestParse:
    def test_summary_and_durations(self):
        text = _log(
            durations=[
                ("12.34", "call", "tests/test_a.py::test_x"),
                ("0.50", "setup", "tests/test_a.py::test_x"),
                ("8.00", "call", "tests/test_b.py::test_y"),
            ]
        )
        total, durs, tail = tier1_budget.parse_log(text)
        assert total == 512.34
        # call + setup aggregate per test id
        assert durs["tests/test_a.py::test_x"] == 12.84
        assert durs["tests/test_b.py::test_y"] == 8.00
        assert "562 passed" in tail

    def test_long_form_summary(self):
        total, _, _ = tier1_budget.parse_log(
            "== 10 passed in 754.21s (0:12:34) ==\n"
        )
        assert total == 754.21

    def test_unparseable_is_none(self):
        total, durs, _ = tier1_budget.parse_log("Killed\n")
        assert total is None and durs == {}


class TestVerdict:
    def _run(self, text, **kw):
        out = io.StringIO()
        total, durs, _ = tier1_budget.parse_log(text)
        rc = tier1_budget.report(
            total,
            durs,
            kw.get("budget", 870.0),
            kw.get("headroom", 0.85),
            kw.get("top", 10),
            out=out,
        )
        return rc, out.getvalue()

    def test_within_budget_passes(self):
        rc, out = self._run(_log(total="512.34s"))
        assert rc == 0 and "OK" in out

    def test_over_headroom_fails_with_offenders(self):
        text = _log(
            total="800.00s",
            durations=[("120.00", "call", "tests/test_big.py::test_z")],
        )
        rc, out = self._run(text)
        assert rc == 1
        assert "OVER" in out and "test_big" in out
        assert "mark.slow" in out

    def test_headroom_knob(self):
        rc, _ = self._run(_log(total="800.00s"), headroom=1.0)
        assert rc == 0

    def test_no_summary_is_a_distinct_error(self):
        rc, out = self._run("Killed\n")
        assert rc == 2 and "no usable suite total" in out

    def test_wall_seconds_override_via_main(self, capsys):
        """This environment's pytest suppresses the summary line (the
        reason tier-1 verify counts dots) — --wall-seconds is the
        reliable total and must win even when a summary parses."""
        import tempfile

        with tempfile.NamedTemporaryFile("w", suffix=".log") as f:
            f.write(_log(total="100.00s"))
            f.flush()
            rc = tier1_budget.main([f.name, "--wall-seconds", "800"])
            assert rc == 1  # 800 > 870 * 0.85, despite the 100s line
            rc = tier1_budget.main(
                [f.name, "--wall-seconds", "500"]
            )
            assert rc == 0

    def test_bare_quiet_summary_parses(self):
        # -q environments that DO print the line omit the == frame
        total, _, _ = tier1_budget.parse_log(
            "734 passed, 44 skipped in 581.20s\n"
        )
        assert total == 581.20
