"""Agent monitors + paral-config tuner: the master→agent→dataloader
retune loop closes end-to-end, and monitoring reaches the SpeedMonitor /
node table through a real served master.

Parity: the reference tests ParalConfigTuner and the monitors against
the in-process local master (test pattern from test_utils.py).
"""

import json
import os
import time

import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.monitor import (
    ParalConfigTuner,
    ResourceMonitor,
    TrainingMonitor,
    report_runtime_metrics,
)
from dlrover_tpu.common import comm
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.trainer.elastic.dataloader import ElasticDataLoader


@pytest.fixture()
def served_master():
    m = start_local_master(node_num=1)
    yield m
    m.stop()


@pytest.fixture()
def client(served_master):
    c = MasterClient(served_master.addr, node_id=0)
    yield c
    c.close()


def _wait_for(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


class TestMonitors:
    def test_resource_monitor_reports_usage(self, served_master, client):
        node = served_master.job_manager.get_node("worker", 0)
        mon = ResourceMonitor(client, interval=0.1)
        mon.start()
        try:
            assert _wait_for(lambda: node.used_resource.memory_mb > 0)
        finally:
            mon.stop()

    def test_training_monitor_feeds_speed_monitor(
        self, served_master, client, tmp_path, monkeypatch
    ):
        metrics_file = str(tmp_path / "metrics.json")
        monkeypatch.setenv(
            "DLROVER_TPU_RUNTIME_METRICS_PATH", metrics_file
        )
        report_runtime_metrics(7, loss=1.5)
        assert json.load(open(metrics_file))["global_step"] == 7

        mon = TrainingMonitor(client, interval=0.1)
        mon.start()
        try:
            sm = served_master.speed_monitor
            assert _wait_for(lambda: sm.completed_global_step == 7)
            report_runtime_metrics(9)
            assert _wait_for(lambda: sm.completed_global_step == 9)
        finally:
            mon.stop()

    def test_paral_config_tuner_end_to_end(
        self, served_master, client, tmp_path
    ):
        """Master sets batch_size → tuner writes the file → a live
        ElasticDataLoader picks it up mid-run (VERDICT weak #5: this loop
        used to be two ends with no middle)."""
        cfg_file = str(tmp_path / "paral.json")
        loader = ElasticDataLoader(
            dataset=list(range(100)), batch_size=4, config_file=cfg_file
        )
        tuner = ParalConfigTuner(client, interval=0.1, path=cfg_file)
        tuner.start()
        try:
            config = comm.ParallelConfig()
            config.dataloader.batch_size = 16
            served_master.paral_config_service.set_global_config(config)
            assert _wait_for(lambda: os.path.exists(cfg_file))
            assert _wait_for(
                lambda: (loader.load_config() or loader.batch_size == 16)
            )
            batch = next(iter(loader))
            assert len(batch) == 16
        finally:
            tuner.stop()

    def test_tuner_rewrites_only_on_new_version(
        self, served_master, client, tmp_path
    ):
        cfg_file = str(tmp_path / "paral.json")
        tuner = ParalConfigTuner(client, interval=0.05, path=cfg_file)
        config = comm.ParallelConfig()
        config.dataloader.batch_size = 8
        served_master.paral_config_service.set_global_config(config)
        tuner.start()
        try:
            assert _wait_for(lambda: os.path.exists(cfg_file))
            mtime = os.path.getmtime(cfg_file)
            time.sleep(0.3)  # several polls, same version
            assert os.path.getmtime(cfg_file) == mtime
        finally:
            tuner.stop()
