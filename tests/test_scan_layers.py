"""scan_layers models: stacked [L, ...] layer params under one lax.scan.

The point (VERDICT r3 #5): the traced graph is O(1) in depth, so deep
models compile WITH remat — the reference's activation-checkpoint
optimization (optimization_library.py:39-58) usable at 48 layers.
Contract: bit-identical math to the unrolled model.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import (
    build_train_step,
    forward,
    init_params,
    init_sharded_state,
    loss_fn,
    shard_batch,
    tiny,
)
from dlrover_tpu.models.transformer import (
    stack_layer_params,
    unstack_layer_params,
)
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh


def _pair(num_layers=4, **kw):
    """(unrolled cfg, scan cfg) with identical weights."""
    cfg = tiny(num_layers=num_layers, **kw)
    scfg = dataclasses.replace(cfg, scan_layers=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    sparams = dict(params)
    sparams["layers"] = stack_layer_params(params["layers"])
    return cfg, scfg, params, sparams


def _tokens(cfg, batch=4, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)


def test_forward_matches_unrolled():
    cfg, scfg, params, sparams = _pair()
    x = _tokens(cfg)
    ref, _ = jax.jit(lambda p, t: forward(p, t, cfg))(params, x)
    got, _ = jax.jit(lambda p, t: forward(p, t, scfg))(sparams, x)
    # same math, but the scanned body compiles as ONE specialization
    # where the unrolled path fuses per layer — last-ulp reassociation
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-5, atol=5e-6
    )


def test_grads_match_unrolled():
    cfg, scfg, params, sparams = _pair()
    x = _tokens(cfg)
    ref_loss, ref_g = jax.jit(
        jax.value_and_grad(lambda p: loss_fn(p, x, x, cfg))
    )(params)
    loss, g = jax.jit(
        jax.value_and_grad(lambda p: loss_fn(p, x, x, scfg))
    )(sparams)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6
        ),
        g["layers"],
        stack_layer_params(ref_g["layers"]),
    )


def test_remat_scan_grads_match():
    """remat over the scanned block must not change the numbers."""
    cfg, scfg, params, sparams = _pair()
    rcfg = dataclasses.replace(scfg, remat=True)
    x = _tokens(cfg)
    base, gb = jax.jit(
        jax.value_and_grad(lambda p: loss_fn(p, x, x, scfg))
    )(sparams)
    rem, gr = jax.jit(
        jax.value_and_grad(lambda p: loss_fn(p, x, x, rcfg))
    )(sparams)
    np.testing.assert_allclose(float(rem), float(base), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        gr,
        gb,
    )


def test_sharded_training_step():
    """scan model trains on an fsdp x dp mesh: the [L, ...] leaves get
    layer_stack-unsharded, embed/mlp axes sharded per the rule table."""
    _, scfg, _, _ = _pair()
    mesh = build_mesh(MeshConfig(fsdp=4, dp=2))
    tx = optax.adamw(1e-2)
    state, sh = init_sharded_state(jax.random.PRNGKey(0), scfg, mesh, tx)
    wq_spec = tuple(sh.params["layers"]["attn"]["wq"].spec)
    assert wq_spec[0] is None, wq_spec  # layer_stack unsharded
    step = build_train_step(scfg, mesh, tx, donate=False)
    x = _tokens(scfg, batch=8)
    b = shard_batch({"x": x, "y": x}, mesh)
    losses = []
    for _ in range(3):
        state, m = step(state, b["x"], b["y"])
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_generation_matches_unrolled():
    from dlrover_tpu.rl.generation import generate

    cfg, scfg, params, sparams = _pair(num_layers=2)
    prompts = jnp.asarray(_tokens(cfg, batch=2, seq=4))
    ref, ref_lp = generate(
        params, prompts, jax.random.PRNGKey(7), cfg,
        max_new_tokens=8, greedy=True,
    )
    got, got_lp = generate(
        sparams, prompts, jax.random.PRNGKey(7), scfg,
        max_new_tokens=8, greedy=True,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    np.testing.assert_allclose(
        np.asarray(got_lp), np.asarray(ref_lp), rtol=1e-5, atol=1e-6
    )


def test_stack_roundtrip_and_guards():
    cfg = tiny(num_layers=3)
    params = init_params(jax.random.PRNGKey(1), cfg)
    rt = unstack_layer_params(stack_layer_params(params["layers"]))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b),
        rt,
        params["layers"],
    )
    with pytest.raises(ValueError, match="homogeneous"):
        tiny(num_experts=2, scan_layers=True)
    from dlrover_tpu.parallel.pipeline import stack_pipeline_params

    scfg = tiny(num_layers=4, scan_layers=True)
    sparams = init_params(jax.random.PRNGKey(0), scfg)
    mesh = build_mesh(MeshConfig(pp=2, dp=4))
    from dlrover_tpu.parallel.pipeline import pipeline_forward

    with pytest.raises(ValueError, match="scan_layers"):
        pipeline_forward(
            stack_pipeline_params(
                init_params(jax.random.PRNGKey(0), tiny(num_layers=4)), 2
            ),
            jnp.asarray(_tokens(scfg)),
            scfg,
            mesh,
            4,
        )


def test_deep_remat_graph_is_constant_size():
    """The jaxpr of a scanned 24-layer model must be ~the same size as
    a 2-layer one (O(1) in depth) — that is the property that lets 48
    layers compile with remat under a bounded-size compile service."""
    x = _tokens(tiny(), batch=2, seq=8)

    def jaxpr_len(L):
        scfg = tiny(num_layers=L, scan_layers=True, remat=True)
        p = init_params(jax.random.PRNGKey(0), scfg)
        jpr = jax.make_jaxpr(
            jax.grad(lambda q: loss_fn(q, x, x, scfg))
        )(p)
        return len(str(jpr))

    small, big = jaxpr_len(2), jaxpr_len(24)
    assert big < 1.5 * small, (small, big)
