"""Resource optimizer, strategy generator and profiler."""

import os

import numpy as np
import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.master.paral_config import ParalConfigService
from dlrover_tpu.master.resource.optimizer import (
    JobResourceOptimizer,
    ResourcePlan,
)
from dlrover_tpu.models import gpt2_small, tiny
from dlrover_tpu.accel.profiler import (
    chip_peak_tflops,
    measure_step,
    profile_model,
)


def _sample(nodes, sps, mem=1000):
    return comm.JobMetricsSample(
        timestamp=0.0,
        alive_nodes=nodes,
        steps_per_sec=sps,
        total_memory_mb=mem,
    )


class TestResourceOptimizer:
    def test_diminishing_returns_recommends_scale_down(self):
        opt = JobResourceOptimizer(min_speedup_per_unit=0.6)
        opt.observe(_sample(4, 10.0))
        opt.observe(_sample(8, 11.0))  # 2x nodes, 1.1x speed: bad deal
        plan = opt.generate_plan()
        assert plan.worker_count == 4
        assert "recommend 4" in plan.reason

    def test_good_scaling_keeps_size(self):
        opt = JobResourceOptimizer(min_speedup_per_unit=0.6)
        opt.observe(_sample(4, 10.0))
        opt.observe(_sample(8, 18.0))  # 1.8x of linear 2x: fine
        plan = opt.generate_plan()
        assert plan.worker_count is None

    def test_memory_rightsizing_and_oom(self):
        class _Coll:
            def snapshot(self):
                return comm.JobMetrics(
                    samples=[_sample(2, 5.0, mem=4000)]
                )

        opt = JobResourceOptimizer(
            metric_collector=_Coll(), memory_headroom=1.5
        )
        plan = opt.generate_plan()
        assert plan.worker_memory_mb == 3000  # 4000/2 * 1.5
        oom = opt.generate_oom_recovery_plan(2048)
        assert oom.worker_memory_mb == 4096

    def test_brain_seam_wins(self):
        opt = JobResourceOptimizer(
            brain=lambda samples: ResourcePlan(
                worker_count=16, reason="cluster"
            )
        )
        assert opt.generate_plan().worker_count == 16

    def test_autoscaler_runs_optimizer_plan(self):
        from dlrover_tpu.master.local_master import LocalJobMaster
        from dlrover_tpu.master.scaler import CallbackScaler

        scaler = CallbackScaler(lambda p: None)
        master = LocalJobMaster(node_num=4, scaler=scaler)
        from dlrover_tpu.common.constants import NodeStatus

        for i in range(4):
            node = master.job_manager.get_node("worker", i)
            node.update_status(NodeStatus.RUNNING)
        opt = JobResourceOptimizer()
        opt.observe(_sample(2, 10.0))
        opt.observe(_sample(4, 11.0))
        master.auto_scaler._optimizer = opt
        master.auto_scaler.run_optimization_pass()
        assert len(master.auto_scaler.alive_nodes()) == 2


class TestStrategyGenerator:
    def test_suggest_from_node_resources(self):
        svc = ParalConfigService()
        cfg = svc.suggest_initial_config(
            batch_size=8, node_cpu=16, node_memory_mb=32000,
            used_memory_mb=8000,
        )
        assert cfg.dataloader.num_workers == 8  # half the cores
        assert cfg.dataloader.batch_size == 24  # 3x headroom
        # capped at 4x
        cfg = svc.suggest_initial_config(
            batch_size=8, node_cpu=4, node_memory_mb=100000,
            used_memory_mb=1000,
        )
        assert cfg.dataloader.batch_size == 32

    def test_passthrough_without_resources(self):
        svc = ParalConfigService()
        cfg = svc.suggest_initial_config(batch_size=8, num_workers=3)
        assert cfg.dataloader.batch_size == 8
        assert cfg.dataloader.num_workers == 3


class TestProfiler:
    def test_gpt2_param_count_matches(self):
        import jax

        from dlrover_tpu.models import init_params

        cfg = tiny()
        prof = profile_model(cfg, batch=4, seq=32)
        params = init_params(jax.random.PRNGKey(0), cfg)
        real = sum(
            int(np.prod(x.shape))
            for x in jax.tree_util.tree_leaves(params)
        )
        # analytic count ignores norm scales (tiny contribution)
        assert abs(prof.total_params - real) / real < 0.01

    def test_flops_scale_with_tokens(self):
        cfg = gpt2_small()
        p1 = profile_model(cfg, batch=1, seq=128)
        p2 = profile_model(cfg, batch=2, seq=128)
        # attention term is superlinear in seq but linear in batch
        assert p2.fwd_flops == pytest.approx(2 * p1.fwd_flops)
        assert "TOTAL" in p1.report()

    def test_gpt2_step_flops_sane(self):
        """6·N·D rule cross-check: GPT-2 124M @ 1024 tokens ≈ 0.88
        TFLOPs/sequence fwd+bwd (±30% for attention/head terms)."""
        cfg = gpt2_small()
        prof = profile_model(cfg, batch=1, seq=1024)
        six_nd = 6.0 * prof.total_params * 1024
        assert prof.step_flops == pytest.approx(six_nd, rel=0.5)

    # slow tier (budget): ~20s of jax.profiler trace + artifact IO;
    # the analytic profiler stays tier-1-covered by the rest of this
    # class and on-demand capture by the obs/flight-recorder tests
    @pytest.mark.slow
    def test_trace_steps_writes_profile(self, tmp_path):
        import glob

        import jax
        import optax

        from dlrover_tpu.accel.profiler import trace_steps
        from dlrover_tpu.models import (
            build_train_step,
            init_sharded_state,
            shard_batch,
        )
        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

        # 1 layer: this exercises trace_steps' profile writing, not
        # the model — every saved compile second keeps tier-1 in budget
        cfg = tiny(num_layers=1)
        mesh = build_mesh(MeshConfig(dp=len(jax.devices())))
        tx = optax.adamw(1e-3)
        state, _ = init_sharded_state(jax.random.PRNGKey(0), cfg, mesh, tx)
        step = build_train_step(cfg, mesh, tx, donate=False)
        x = np.zeros((8, 16), np.int32)
        b = shard_batch({"x": x, "y": x}, mesh)
        out = trace_steps(
            step, state, (b["x"], b["y"]), str(tmp_path / "trace"), steps=2
        )
        traces = glob.glob(os.path.join(out, "**", "*.trace*"), recursive=True)
        assert traces, os.listdir(out)

    def test_measure_step(self):
        import jax
        import optax

        from dlrover_tpu.models import (
            build_train_step,
            init_sharded_state,
            shard_batch,
        )
        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

        cfg = tiny()
        mesh = build_mesh(MeshConfig(dp=len(jax.devices())))
        tx = optax.adamw(1e-3)
        state, _ = init_sharded_state(jax.random.PRNGKey(0), cfg, mesh, tx)
        step = build_train_step(cfg, mesh, tx, donate=False)
        x = np.zeros((8, 32), np.int32)
        b = shard_batch({"x": x, "y": x}, mesh)
        prof = profile_model(cfg, batch=8, seq=32)
        m = measure_step(step, state, (b["x"], b["y"]), prof.step_flops, iters=3)
        assert m.step_seconds > 0 and m.achieved_tflops > 0


def test_module_breakdown_measures_each_module():
    """The AProfiler analog: per-module measured latency + achieved
    TFLOP/s for embed / block fwd / block fwd+bwd / head / optimizer."""
    import optax

    from dlrover_tpu.accel.profiler import module_breakdown
    from dlrover_tpu.models import tiny

    cfg = tiny(num_layers=2, dtype="float32")
    rows = module_breakdown(cfg, optax.adamw(1e-3), batch=4, seq=32, iters=3)
    names = [r.name for r in rows]
    assert names == [
        "embed", "block_fwd", "block_fwd_bwd", "lm_head_fwd_bwd",
        "optimizer_update",
    ]
    for r in rows:
        assert r.ms > 0
    bwd = dict((r.name, r) for r in rows)
    # fwd+bwd must cost more than fwd alone, and carry ~3x the flops
    assert bwd["block_fwd_bwd"].ms > bwd["block_fwd"].ms
    assert bwd["block_fwd_bwd"].gflops == pytest.approx(
        3 * bwd["block_fwd"].gflops, rel=0.05
    )
