"""Serve-while-training (ISSUE 17): seqlock weight publication on the
shm handler, the zero-copy subscriber, the co-located serving engine,
and the ``serving_soak`` goodput row.

Acceptance anchors:
- a reader racing ``begin_save``→``commit_save`` can never observe a
  torn frame (generation re-check catches a commit landing inside the
  widened ``serve.stale_read`` window);
- the subscribe path is zero-copy (records alias the subscriber's own
  shm mapping — no host memcpy);
- a crc mismatch names the offending record (typed ``ShmCrcError``)
  and the subscriber skips that generation without crashing;
- the engine swaps weights only between batches and serves tokens
  bitwise-identical to decoding under the published params directly;
- ``serving_soak`` ranks below every training category: a serving
  episode overlapping a ``compute`` span claims nothing.
"""

import itertools
import threading
import time

import numpy as np
import pytest

from dlrover_tpu.common import faults
from dlrover_tpu.ckpt.shm_handler import (
    ShmCrcError,
    ShmHandler,
    ShmSubscriber,
    data_crc32,
)
from dlrover_tpu.ckpt.sharding import host_shard_records
from dlrover_tpu.obs import goodput as obs_goodput
from dlrover_tpu.obs.goodput import GoodputLedger
from dlrover_tpu.obs.trace import SpanTracer
from dlrover_tpu.parallel import transfer_sched

MS = 1_000_000  # ns

# each test gets its own shard rank: shm segment + meta-dict socket
# names are rank-scoped, so tests can't see each other's publications
_RANKS = itertools.count(40)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _state(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "b": rng.normal(size=(4,)).astype(np.float32),
        "w": rng.normal(size=(8, 4)).astype(np.float32),
    }


@pytest.fixture
def chan():
    """One publication channel: a writer plus a subscriber factory."""
    rank = next(_RANKS)
    writer = ShmHandler(rank, create=True)
    subs = []

    def subscribe(**kw):
        s = ShmSubscriber(rank, **kw)
        subs.append(s)
        return s

    yield writer, subscribe
    for s in subs:
        s.close()
    writer.close(unlink=True)


class TestSeqlockPublication:
    def test_generation_parity_and_monotonicity(self, chan):
        writer, _ = chan
        recs = host_shard_records(_state())
        writer.save_records(1, recs, {})
        meta = writer.metadata()
        assert meta["valid"] and meta["gen"] % 2 == 0
        g0 = meta["gen"]
        total = sum(r.data.nbytes for r in recs)
        writer.begin_save(total)
        mid = writer.metadata()
        assert not mid["valid"] and mid["gen"] % 2 == 1
        assert mid["gen"] > g0
        metas = writer.layout_records(recs)
        for r, m in zip(recs, metas):
            m.crc32 = data_crc32(r.data)
            writer.write_chunk(m.offset, r.data)
        writer.commit_save(2, metas, {})
        done = writer.metadata()
        assert done["valid"] and done["gen"] % 2 == 0
        assert done["gen"] > mid["gen"]

    def test_subscriber_maps_zero_copy(self, chan):
        writer, subscribe = chan
        state = _state()
        writer.save_records(5, host_shard_records(state), {})
        sub = subscribe()
        frame = sub.poll()
        assert frame is not None and frame.step == 5
        # zero-copy: every record aliases the subscriber's OWN mapping
        seg = np.frombuffer(sub.handler._shm.buf, dtype=np.uint8)
        for r in frame.records:
            assert np.shares_memory(r.data, seg)
        np.testing.assert_array_equal(
            frame.by_path()["w"].data, state["w"]
        )
        del frame, seg

    def test_no_new_commit_returns_none(self, chan):
        writer, subscribe = chan
        writer.save_records(1, host_shard_records(_state()), {})
        sub = subscribe()
        assert sub.poll() is not None
        assert sub.poll() is None  # same generation: nothing new
        writer.save_records(2, host_shard_records(_state(1)), {})
        frame = sub.poll()
        assert frame is not None and frame.step == 2
        del frame

    def test_mid_write_frame_invisible(self, chan):
        writer, subscribe = chan
        recs = host_shard_records(_state())
        writer.save_records(1, recs, {})
        sub = subscribe()
        assert sub.poll() is not None
        writer.begin_save(sum(r.data.nbytes for r in recs))
        # save open: generation is odd, metadata invalid — no frame
        assert sub.poll() is None
        assert sub.torn_retries == 0

    def test_torn_frame_caught_by_generation_recheck(self, chan):
        """Commit mid-read: `serve.stale_read:delay` widens the window
        between the zero-copy map and the seqlock re-check; a full
        save landing inside it MUST be detected and the frame dropped
        (never handed out torn)."""
        writer, subscribe = chan
        recs = host_shard_records(_state())
        writer.save_records(1, recs, {})
        sub = subscribe()
        assert sub.poll() is not None
        writer.save_records(2, host_shard_records(_state(2)), {})
        faults.configure("serve.stale_read:delay:1.0")

        def racing_commit():
            time.sleep(0.02)  # lands inside the 50 ms DELAY_S window
            writer.save_records(3, host_shard_records(_state(3)), {})

        t = threading.Thread(target=racing_commit)
        t.start()
        frame = sub.poll()  # maps gen of step 2, re-check sees step 3
        t.join()
        assert frame is None
        assert sub.torn_retries == 1
        faults.reset()
        frame = sub.poll()  # the racing commit is clean and newest
        assert frame is not None and frame.step == 3
        del frame

    def test_restarted_writer_continues_generation(self, chan):
        writer, subscribe = chan
        writer.save_records(1, host_shard_records(_state()), {})
        g0 = writer.metadata()["gen"]
        sub = subscribe()
        assert sub.poll() is not None
        # a writer restart attaches the same meta dict: generations
        # must continue forward, never rewind the subscriber
        writer2 = ShmHandler(writer.local_rank, create=False)
        try:
            writer2.save_records(
                2, host_shard_records(_state(1)), {}
            )
            assert writer2.metadata()["gen"] > g0
            frame = sub.poll()
            assert frame is not None and frame.step == 2
            del frame
        finally:
            writer2.close()  # drops its own mapping; no unlink


class TestCrcGate:
    def _publish_rotten(self, writer, state, step, seed=7):
        """Publish ``state`` with one seeded bit flipped in flight
        (after the writer's checksum) — detectable rot."""
        faults.configure(f"ckpt.shm_stage:bit_flip:@1:{seed}")
        try:
            writer.save_records(step, host_shard_records(state), {})
        finally:
            faults.reset()

    def test_typed_error_names_the_record(self, chan):
        writer, _ = chan
        self._publish_rotten(writer, _state(), 1)
        with pytest.raises(ShmCrcError) as ei:
            writer.load_records(verify=True)
        err = ei.value
        assert err.record == "b" and err.index == 0
        assert err.want != err.got
        assert "b" in str(err) and "checksum mismatch" in str(err)
        assert isinstance(err, ValueError)  # saver's handler still works

    def test_subscriber_skips_rotten_generation(self, chan):
        writer, subscribe = chan
        writer.save_records(1, host_shard_records(_state()), {})
        sub = subscribe()
        f1 = sub.poll()
        assert f1 is not None and f1.step == 1
        del f1
        self._publish_rotten(writer, _state(2), 2)
        assert sub.poll() is None  # skipped, not raised
        assert sub.crc_retries == 1
        assert sub.last_crc_record == "b"
        # repolling the SAME generation must not spin the counter
        assert sub.poll() is None
        assert sub.crc_retries == 1
        # retry-next-commit: the next clean publication is adopted
        writer.save_records(3, host_shard_records(_state(3)), {})
        f3 = sub.poll()
        assert f3 is not None and f3.step == 3
        del f3

    def test_subscribe_fault_site_raises_through(self, chan):
        writer, subscribe = chan
        writer.save_records(1, host_shard_records(_state()), {})
        sub = subscribe()
        faults.configure("serve.subscribe:io_error:@1")
        with pytest.raises(OSError):
            sub.poll()
        faults.reset()
        frame = sub.poll()  # caller retries; publication unharmed
        assert frame is not None and frame.step == 1
        del frame

    def test_wait_for_commit_times_out_and_delivers(self, chan):
        writer, subscribe = chan
        sub = subscribe()
        assert sub.wait_for_commit(timeout=0.05, interval=0.01) is None
        writer.save_records(4, host_shard_records(_state()), {})
        frame = sub.wait_for_commit(timeout=2.0, interval=0.01)
        assert frame is not None and frame.step == 4
        del frame


@pytest.fixture(scope="module")
def served_model():
    import jax

    from dlrover_tpu.models import tiny
    from dlrover_tpu.models.transformer import init_params

    cfg = tiny(vocab_size=31, num_layers=1, max_seq_len=32)
    p0 = jax.jit(lambda k: init_params(k, cfg))(jax.random.PRNGKey(3))
    p1 = jax.jit(lambda k: init_params(k, cfg))(jax.random.PRNGKey(4))
    return cfg, p0, p1


def _prompts(cfg, n=3, p_max=6, seed=0):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    lens = rng.integers(2, p_max + 1, size=n).astype(np.int32)
    toks = np.zeros((n, p_max), np.int32)
    for i, ln in enumerate(lens):
        toks[i, :ln] = rng.integers(1, cfg.vocab_size, size=ln)
    return jnp.asarray(toks), jnp.asarray(lens)


def _decode_direct(cfg, params, prompts, lens, scfg):
    import jax

    from dlrover_tpu.rl.continuous_batching import continuous_generate

    return continuous_generate(
        params, prompts, lens, jax.random.PRNGKey(0), cfg,
        max_new_tokens=scfg.max_new_tokens, eos_id=scfg.eos_id,
        slots=scfg.slots, greedy=True,
    )


class TestServingEngine:
    def _engine(self, chan, cfg, template, **kw):
        import jax.numpy as jnp
        import jax

        from dlrover_tpu.serve import ServingConfig, ServingEngine

        _, subscribe = chan
        scfg = ServingConfig(
            max_new_tokens=4, slots=2, soak=kw.pop("soak", "always"),
            **kw,
        )
        zeros = jax.tree_util.tree_map(jnp.zeros_like, template)
        return ServingEngine(cfg, subscribe(), zeros, scfg), scfg

    def test_swap_and_bitwise_decode(self, chan, served_model):
        import jax

        cfg, p0, _ = served_model
        writer, _ = chan
        eng, scfg = self._engine(chan, cfg, p0)
        with pytest.raises(RuntimeError):
            eng.serve_batch(*_prompts(cfg), jax.random.PRNGKey(0))
        writer.save_records(10, host_shard_records(p0), {})
        assert eng.try_swap()
        assert eng.weight_step == 10 and eng.swaps == 1
        prompts, lens = _prompts(cfg)
        got = eng.serve_batch(prompts, lens, jax.random.PRNGKey(0))
        want = _decode_direct(cfg, p0, prompts, lens, scfg)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))

    def test_swap_only_between_batches_tracks_staleness(
        self, chan, served_model
    ):
        import jax

        cfg, p0, p1 = served_model
        writer, _ = chan
        eng, scfg = self._engine(chan, cfg, p0)
        writer.save_records(10, host_shard_records(p0), {})
        assert eng.try_swap()
        # step 12 commits, but no try_swap yet: the engine keeps
        # serving step 10 (never swaps mid-stream) and reports the lag
        writer.save_records(12, host_shard_records(p1), {})
        assert eng.staleness_steps() == 2
        prompts, lens = _prompts(cfg, seed=1)
        got = eng.serve_batch(prompts, lens, jax.random.PRNGKey(0))
        want = _decode_direct(cfg, p0, prompts, lens, scfg)
        np.testing.assert_array_equal(
            np.asarray(got[0]), np.asarray(want[0])
        )
        assert eng.try_swap()
        assert eng.weight_step == 12 and eng.staleness_steps() == 0
        got = eng.serve_batch(prompts, lens, jax.random.PRNGKey(0))
        want = _decode_direct(cfg, p1, prompts, lens, scfg)
        np.testing.assert_array_equal(
            np.asarray(got[0]), np.asarray(want[0])
        )

    def test_swap_fault_keeps_previous_weights(self, chan, served_model):
        cfg, p0, p1 = served_model
        writer, _ = chan
        eng, _ = self._engine(chan, cfg, p0)
        writer.save_records(10, host_shard_records(p0), {})
        assert eng.try_swap()
        writer.save_records(11, host_shard_records(p1), {})
        faults.configure("serve.swap:io_error:@1")
        assert not eng.try_swap()  # fails closed
        assert eng.weight_step == 10
        faults.reset()
        # the frame was consumed by the failed poll generation? no —
        # the subscriber adopted the generation before the swap fired,
        # so a NEW commit is what retries; publish again
        writer.save_records(13, host_shard_records(p1), {})
        assert eng.try_swap()
        assert eng.weight_step == 13

    def test_idle_gap_gate(self, chan, served_model):
        cfg, p0, _ = served_model
        eng, _ = self._engine(
            chan, cfg, p0, soak="idle_gaps",
            gap_wait_timeout_s=0.05, gap_poll_interval_s=0.005,
        )
        try:
            transfer_sched.note_compute(True)
            assert transfer_sched.get_arbiter().in_compute_window()
            assert not eng._wait_for_gap()  # timed out inside compute
            transfer_sched.note_compute(False)
            assert not transfer_sched.get_arbiter().in_compute_window()
            assert eng._wait_for_gap()
        finally:
            transfer_sched.note_compute(False)


class TestServingGoodput:
    def _ledger(self):
        tr = SpanTracer(enabled=True)
        led = GoodputLedger(tracer=tr, tid_fn=lambda: 1)
        led._t0_ns -= 1_000 * MS
        led._last_ns -= 1_000 * MS
        return tr, led, led._last_ns

    @staticmethod
    def _put(tracer, name, start_ns, dur_ns, tid=1, depth=0):
        tracer._buf.append(
            (name, tid, start_ns, dur_ns, depth, None,
             next(tracer._seq))
        )
        tracer._appended += 1

    def test_serving_soak_claims_only_idle_time(self):
        """serving_soak ranks below productive_compute: a serving
        episode overlapping a compute span claims only the part
        training left unclaimed — `fleet_goodput` is untouched."""
        tr, led, t0 = self._ledger()
        self._put(tr, "compute", t0, 100 * MS)
        # serving runs 60..180ms: 40ms under compute, 80ms in the gap
        led.mark_interval("serving_soak", t0 + 60 * MS, t0 + 180 * MS)
        rep = led.snapshot(now_ns=t0 + 200 * MS)
        assert rep.seconds["productive_compute"] == pytest.approx(0.100)
        assert rep.seconds["serving_soak"] == pytest.approx(0.080)
        assert rep.goodput_pct == pytest.approx(50.0)
        assert rep.closure_error_pct == pytest.approx(0.0, abs=1e-6)

    def test_serving_episode_channel(self):
        _, led, _ = self._ledger()
        led.serving_begin()
        time.sleep(0.03)
        led.serving_end()
        rep = led.snapshot()
        assert rep.seconds["serving_soak"] >= 0.025
        assert rep.closure_error_pct == pytest.approx(0.0, abs=1e-6)

    def test_note_serving_seam(self, monkeypatch):
        _, led, _ = self._ledger()
        monkeypatch.setattr(obs_goodput, "_default", None)
        obs_goodput.note_serving(True)  # no ledger: must not raise
        obs_goodput.install_default_ledger(led)
        obs_goodput.note_serving(True)
        time.sleep(0.02)
        obs_goodput.note_serving(False)
        assert led.snapshot().seconds["serving_soak"] >= 0.015
        monkeypatch.setattr(obs_goodput, "_default", None)
