"""Elastic sampler / dataloader / sharding-client tests."""

import json
import os

import numpy as np
import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.sharding_client import (
    IndexShardingClient,
    ShardingClient,
)
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.trainer.elastic.dataloader import ElasticDataLoader
from dlrover_tpu.trainer.elastic.sampler import ElasticDistributedSampler


class TestSampler:
    def test_partition_no_overlap(self):
        samplers = [
            ElasticDistributedSampler(
                100, num_replicas=4, rank=r, shuffle=False
            )
            for r in range(4)
        ]
        seen = [list(s) for s in samplers]
        flat = sorted(i for part in seen for i in part)
        assert flat == sorted(set(flat))  # disjoint
        assert len(flat) == 100

    def test_replicas_exceed_dataset_all_ranks_step_equally(self):
        # num_replicas > dataset_size: padding must wrap repeatedly so
        # every rank yields the same count (else collectives hang)
        samplers = [
            ElasticDistributedSampler(3, num_replicas=8, rank=r)
            for r in range(8)
        ]
        assert all(len(s) == 1 for s in samplers)
        counts = {r: len(list(s)) for r, s in enumerate(samplers)}
        assert set(counts.values()) == {1}

    def test_shuffle_deterministic_across_ranks(self):
        a = list(
            ElasticDistributedSampler(50, 2, 0, shuffle=True, seed=7)
        ) + list(ElasticDistributedSampler(50, 2, 1, shuffle=True, seed=7))
        assert sorted(a) == list(range(50))

    def test_mid_epoch_resume_same_world(self):
        s = ElasticDistributedSampler(40, num_replicas=2, rank=0, shuffle=False)
        it = iter(s)
        consumed = [next(it) for _ in range(5)]
        state = s.state_dict()
        assert state["completed_num"] == 10  # 5 yields x 2 replicas

        s2 = ElasticDistributedSampler(40, num_replicas=2, rank=0, shuffle=False)
        s2.load_state_dict(state)
        rest = list(s2)
        assert consumed + rest == list(range(0, 40, 2))

    def test_mid_epoch_resume_world_change(self):
        """Resume with a different replica count: remaining samples are
        re-dealt; nothing is skipped or duplicated."""
        s = ElasticDistributedSampler(24, num_replicas=2, rank=0, shuffle=False)
        it = iter(s)
        for _ in range(4):
            next(it)
        state = s.state_dict()  # 8 consumed globally

        parts = []
        for r in range(3):  # world grew to 3
            s2 = ElasticDistributedSampler(
                24, num_replicas=3, rank=r, shuffle=False
            )
            s2.load_state_dict(state)
            parts.append(list(s2))
        remaining = sorted(i for p in parts for i in p)
        assert remaining == list(range(8, 24))  # exactly the tail, once

    def test_load_state_past_end_rolls_epoch(self):
        s = ElasticDistributedSampler(10, num_replicas=2, rank=0)
        s.load_state_dict({"epoch": 0, "completed_num": 10})
        assert s.epoch == 1
        assert s.completed_num == 0


class TestDataLoader:
    def test_batches(self):
        data = np.arange(20)
        dl = ElasticDataLoader(data, batch_size=6)
        batches = list(dl)
        assert [len(b) for b in batches] == [6, 6, 6, 2]
        assert np.concatenate(batches).tolist() == list(range(20))

    def test_paral_config_reload(self, tmp_path):
        cfg = tmp_path / "paral.json"
        cfg.write_text(json.dumps({"dataloader": {"batch_size": 4}}))
        dl = ElasticDataLoader(
            np.arange(8), batch_size=2, config_file=str(cfg)
        )
        assert dl.batch_size == 4

    def test_tuple_collate(self):
        data = [(np.ones(3), np.zeros(1)) for _ in range(4)]
        dl = ElasticDataLoader(data, batch_size=2)
        xb, yb = next(iter(dl))
        assert xb.shape == (2, 3) and yb.shape == (2, 1)


class TestShardingClient:
    @pytest.fixture(scope="class")
    def master(self):
        m = start_local_master(node_num=2)
        yield m
        m.stop()

    def test_shard_stream(self, master):
        client = MasterClient(master.addr, node_id=0)
        sc = ShardingClient(
            client, "sc-ds", batch_size=4, dataset_size=32,
            num_minibatches_per_shard=2,
        )
        total = 0
        while True:
            shard = sc.fetch_shard()
            if shard is None:
                break
            total += shard.end - shard.start
            sc.report_shard_done()
        assert total == 32
        client.close()

    def test_index_stream(self, master):
        client = MasterClient(master.addr, node_id=1)
        isc = IndexShardingClient(
            client, "isc-ds", batch_size=2, dataset_size=10,
            num_minibatches_per_shard=1,
        )
        indices = list(isc)
        assert sorted(indices) == list(range(10))
        client.close()


class TestMemmapTokenDataset:
    def test_roundtrip_and_windows(self, tmp_path):
        from dlrover_tpu.data.token_dataset import (
            MemmapTokenDataset,
            write_tokens,
        )

        toks = np.arange(100, dtype=np.int64) % 50257
        path = str(tmp_path / "corpus.bin")
        write_tokens(path, toks)
        ds = MemmapTokenDataset(path, seq_len=16)
        # 100 tokens, windows need 17: (100-17)//16+1 = 6 disjoint items
        assert len(ds) == 6
        item = ds[0]
        np.testing.assert_array_equal(item["x"], toks[:16])
        np.testing.assert_array_equal(item["y"], toks[1:17])
        item = ds[5]
        np.testing.assert_array_equal(item["x"], toks[80:96])
        # big-vocab corpora get uint32 automatically
        big = np.array([0, 70000, 5], dtype=np.int64)
        path2 = str(tmp_path / "big.bin")
        write_tokens(path2, big)
        ds2 = MemmapTokenDataset(path2, seq_len=1)
        assert int(ds2[0]["y"][0]) == 70000

    def test_feeds_elastic_trainer(self, tmp_path):
        """The memmap dataset plugs straight into ElasticTrainer (the
        sampler shards/resumes over its windows)."""
        import optax

        from dlrover_tpu.accel.strategy import Strategy
        from dlrover_tpu.ckpt.saver import AsyncCheckpointSaver
        from dlrover_tpu.data.token_dataset import (
            MemmapTokenDataset,
            write_tokens,
        )
        from dlrover_tpu.models import tiny
        from dlrover_tpu.parallel.mesh import MeshConfig
        from dlrover_tpu.trainer.elastic.trainer import (
            ElasticTrainer,
            TrainerConfig,
        )

        rng = np.random.default_rng(0)
        path = str(tmp_path / "c.bin")
        write_tokens(path, rng.integers(0, 256, 4096))
        AsyncCheckpointSaver.reset()
        t = ElasticTrainer(
            model_cfg=tiny(),
            tx=optax.adamw(1e-2),
            dataset=MemmapTokenDataset(path, seq_len=32),
            trainer_cfg=TrainerConfig(
                batch_size=8, seq_len=32, report_metrics=False,
                log_interval=10,
            ),
            strategy=Strategy(mesh=MeshConfig(dp=8), dtype="float32"),
        )
        losses = []
        t._metrics_hook = lambda s, m: losses.append(float(m["loss"]))
        t.train(num_steps=5)
        assert losses[-1] < losses[0]
        t.close()

    def test_corrupt_meta_fails_loudly(self, tmp_path):
        """A PRESENT but unreadable meta must raise, never fall back to
        uint16 (silent garbage); only a MISSING meta means headerless."""
        from dlrover_tpu.data.token_dataset import (
            MemmapTokenDataset,
            write_tokens,
        )

        path = str(tmp_path / "c.bin")
        write_tokens(path, np.arange(64) % 256)
        with open(f"{path}.meta.json", "w") as f:
            f.write("{not json")
        with pytest.raises(ValueError, match="unreadable"):
            MemmapTokenDataset(path, seq_len=8)
        # headerless (no meta at all): opens as uint16
        raw = str(tmp_path / "plain.bin")
        np.arange(64, dtype=np.uint16).tofile(raw)
        ds = MemmapTokenDataset(raw, seq_len=8)
        assert len(ds) > 0

    def test_rewrite_is_atomic_for_readers(self, tmp_path):
        """A reader opening during a dtype-changing rewrite always pairs
        a meta with exactly the data file it names (generation-suffixed
        files; the meta replace is the commit point)."""
        from dlrover_tpu.data.token_dataset import (
            MemmapTokenDataset,
            write_tokens,
        )

        path = str(tmp_path / "c.bin")
        write_tokens(path, np.full(40, 70000))  # uint32 corpus
        ds_old = MemmapTokenDataset(path, seq_len=8)
        assert int(ds_old[0]["x"][0]) == 70000
        write_tokens(path, np.arange(40) % 100)  # rewritten as uint16
        ds_new = MemmapTokenDataset(path, seq_len=8)
        assert int(ds_new[0]["x"][1]) == 1  # decoded correctly
        # the old handle keeps reading ITS generation coherently
        assert int(ds_old[0]["x"][0]) == 70000

    def test_dtype_override_and_gc_precision(self, tmp_path):
        from dlrover_tpu.data.token_dataset import (
            MemmapTokenDataset,
            write_tokens,
        )

        path = str(tmp_path / "c.bin")
        # unrelated sibling that must SURVIVE generation GC
        bystander = str(tmp_path / "c.bin.gz")
        open(bystander, "wb").write(b"backup")
        write_tokens(path, np.arange(64) % 256)
        write_tokens(path, np.arange(64) % 256)  # triggers GC
        assert os.path.exists(bystander)
        # explicit dtype= still resolves the generation-suffixed file
        ds = MemmapTokenDataset(path, seq_len=8, dtype="uint16")
        assert int(ds[0]["x"][1]) == 1
