"""Step-budget reconciliation (ISSUE 19): priced-vs-observed
attribution, drift-vs-regression classification, calibration
persistence, and the fleet leg of the attribution.

Acceptance anchors:
- observed component seconds come from the span stream's step windows
  (same clipping rule as ``step_coverage``): a span straddling a mesh
  rebuild contributes only its inside portion to each step bucket —
  never double-counted into a neighbor step;
- a mispriced component (within the drift gate) folds into the
  per-component EWMA and raises NO regression alarm; a genuinely
  regressed component trips the CUSUM latch, names itself, and fires
  ``on_alarm`` once per episode;
- the drift snapshot persists beside ``railrates-<fp>.json`` with the
  same fingerprint-reject discipline, and the dry-runner reprices
  per component (``reprice_report``) instead of one scalar calib;
- the aggregator upgrades a straggler flag with the component-level
  *why*, and ``merge_timeline`` renders alarms as named instant
  markers.
"""

import json
import os

import pytest

from dlrover_tpu.obs import audit as obs_audit
from dlrover_tpu.obs.audit import (
    COMPONENTS,
    CUSUM_H,
    CUSUM_K,
    WARMUP_STEPS,
    AuditCalibration,
    ComponentDrift,
    CusumDetector,
    StepAuditor,
    StepBudget,
    current_drift_factors,
    install_default_auditor,
    load_audit_calibration,
    reset_default_auditor,
    save_audit_calibration,
    seed_default_drift,
)
from dlrover_tpu.obs.metrics import MetricsRegistry
from dlrover_tpu.obs.trace import SpanTracer, step_coverage

MS = 1_000_000  # ns


@pytest.fixture(autouse=True)
def _isolated_default_auditor(tmp_path, monkeypatch):
    # hermetic: the user cache may hold a real auditcal-<fp>.json from
    # any prior trainer run on this machine — current_drift_factors()
    # overlays it by design, so point the topology cache elsewhere
    monkeypatch.setenv(
        "DLROVER_TPU_TOPOLOGY_CACHE", str(tmp_path / "topocache")
    )
    reset_default_auditor()
    yield
    reset_default_auditor()


def _put(tracer, name, start_ns, dur_ns, tid=1, depth=0):
    """Append one synthetic completed record (drain input shape)."""
    tracer._buf.append(
        (name, tid, start_ns, dur_ns, depth, None, next(tracer._seq))
    )
    tracer._appended += 1


def _emit_step(tracer, t0_ns, *, compute_ms=80.0, data_wait_ms=5.0,
               host_sync_ms=0.0, tid=1):
    """One complete step: children first, then the parent ``step``
    record — the stack-discipline drain order the auditor sees live.
    Returns the step's end time in ns."""
    t = t0_ns
    if data_wait_ms:
        _put(tracer, "data_wait", t, int(data_wait_ms * MS), tid, depth=1)
        t += int(data_wait_ms * MS)
    if compute_ms:
        _put(tracer, "compute", t, int(compute_ms * MS), tid, depth=1)
        t += int(compute_ms * MS)
    if host_sync_ms:
        _put(tracer, "host_sync", t, int(host_sync_ms * MS), tid, depth=1)
        t += int(host_sync_ms * MS)
    _put(tracer, "step", t0_ns, t - t0_ns, tid, depth=0)
    return t


def _budget(compute_ms=80.0, data_wait_ms=5.0, **kw):
    b = StepBudget()
    b.set_component("compute", compute_ms / 1e3, "priced")
    b.set_component("data_wait", data_wait_ms / 1e3, "priced")
    for c, ms in kw.items():
        b.set_component(c, ms / 1e3, "priced")
    return b


def _auditor(budget=None, **kw):
    tr = SpanTracer(enabled=True)
    aud = StepAuditor(tracer=tr, budget=budget, **kw)
    return tr, aud


def _run_warmup(tr, aud, t0=0, **step_kw):
    """Drive the auditor past its baseline window on on-budget steps."""
    t = t0
    for _ in range(WARMUP_STEPS):
        t = _emit_step(tr, t, **step_kw)
    aud.collect()
    return t


class TestStepBudget:
    def test_component_roundtrip_and_total(self):
        b = StepBudget()
        for i, c in enumerate(COMPONENTS):
            b.set_component(c, 0.01 * (i + 1), "priced")
        assert b.component("dcn_sync") == pytest.approx(0.03)
        assert b.total_s() == pytest.approx(sum(
            0.01 * (i + 1) for i in range(len(COMPONENTS))
        ))
        d = b.as_dict()
        assert d["source"]["compute"] == "priced"
        assert set(d) == {c + "_s" for c in COMPONENTS} | {"source"}

    def test_negative_clamps_to_zero(self):
        b = StepBudget()
        b.set_component("compute", -1.0)
        assert b.compute_s == 0.0


class TestComponentDrift:
    def test_seed_is_first_measurement_only(self):
        d = ComponentDrift()
        d.seed(1.8)
        assert d.factor == pytest.approx(1.8)
        d.seed(5.0)  # no-op once seeded
        assert d.factor == pytest.approx(1.8)

    def test_fold_ewma_converges(self):
        d = ComponentDrift()
        for _ in range(60):
            d.fold(1.5)
        assert d.factor == pytest.approx(1.5, rel=1e-3)

    def test_nonpositive_ratio_ignored(self):
        d = ComponentDrift()
        d.fold(0.0)
        d.seed(-2.0)
        assert d.factor == 1.0 and d.samples == 0


class TestCusumDetector:
    def test_sustained_positive_fires_and_resets(self):
        det = CusumDetector()
        fired = [det.update(2.0) for _ in range(5)]
        assert any(fired)
        # the accumulator reset on fire: re-alarming needs
        # re-accumulation (refire hysteresis)
        assert det.pos < CUSUM_H

    def test_noise_below_allowance_never_fires(self):
        det = CusumDetector()
        for r in (0.1, -0.2, 0.2, -0.1) * 50:
            assert not det.update(r)

    def test_fast_side_tracked_but_silent(self):
        det = CusumDetector()
        for _ in range(10):
            assert not det.update(-2.0)
        assert det.neg > 0.0


class TestAuditorObservation:
    def test_on_budget_steps_no_alarm(self):
        tr, aud = _auditor(_budget())
        t = _run_warmup(tr, aud)
        for _ in range(5):
            t = _emit_step(tr, t)
        results = aud.collect()
        assert len(results) == 5
        assert aud.steps_audited == WARMUP_STEPS + 5
        assert aud.alarm_components() == []
        last = aud.last_result()
        assert last.observed["compute"] == pytest.approx(0.08, rel=1e-6)
        assert abs(last.residual["compute"]) < 1e-6

    def test_children_of_inflight_step_are_held(self):
        tr, aud = _auditor(_budget())
        _put(tr, "compute", 0, 80 * MS, depth=1)  # step not closed yet
        assert aud.collect() == []
        _put(tr, "step", 0, 85 * MS, depth=0)
        res = aud.collect()
        assert len(res) == 1
        assert res[0].observed["compute"] == pytest.approx(0.08)

    def test_other_tid_records_ignored(self):
        tr, aud = _auditor(_budget(), tid_fn=lambda: 1)
        _emit_step(tr, 0, tid=2)
        assert aud.collect() == []

    def test_measured_sync_deducted_from_compute(self):
        b = _budget(ici_sync=0.0)
        b.set_component("ici_sync", 0.01, "priced")
        tr, aud = _auditor(b)
        aud.set_measured("ici_sync", 0.01)
        _emit_step(tr, 0, compute_ms=90.0)  # sync runs inside compute
        res = aud.collect()[0]
        assert res.observed["ici_sync"] == pytest.approx(0.01)
        assert res.observed["compute"] == pytest.approx(0.08)

    def test_unknown_component_rejected(self):
        _tr, aud = _auditor()
        with pytest.raises(ValueError):
            aud.set_measured("gpu_burn", 1.0)
        with pytest.raises(ValueError):
            aud.seed_drift("gpu_burn", 1.0)


class TestDriftVsRegression:
    def test_mispricing_within_gate_folds_no_alarm(self):
        # compute consistently 1.6x its price: drift, not regression
        tr, aud = _auditor(_budget(compute_ms=50.0))
        alarms = []
        aud._on_alarm = lambda c, r, d: alarms.append(c)
        t = 0
        for _ in range(WARMUP_STEPS + 15):
            t = _emit_step(tr, t, compute_ms=80.0)
        aud.collect()
        assert alarms == []
        assert aud.alarm_components() == []
        assert aud.drift_factors()["compute"] == pytest.approx(1.6, abs=0.05)

    def test_regression_beyond_gate_alarms_right_component(self):
        tr, aud = _auditor(_budget())
        fired = []
        aud._on_alarm = lambda c, r, d: fired.append((c, r, d))
        t = _run_warmup(tr, aud)
        # data_wait blows past the 2x drift gate; compute stays on-price
        for _ in range(10):
            t = _emit_step(tr, t, data_wait_ms=25.0)
        aud.collect()
        assert [c for c, _, _ in fired] == ["data_wait"]
        assert "data_wait" in aud.alarm_components()
        assert "compute" not in aud.alarm_components()
        c, ratio, detail = fired[0]
        assert ratio > 2.0
        assert detail.startswith("data_wait ")
        assert aud.alarms_total()["data_wait"] >= 1

    def test_alarm_fires_once_per_episode_and_clears(self):
        tr, aud = _auditor(_budget())
        fired = []
        aud._on_alarm = lambda c, r, d: fired.append(c)
        t = _run_warmup(tr, aud)
        for _ in range(12):
            t = _emit_step(tr, t, data_wait_ms=25.0)
        aud.collect()
        assert fired.count("data_wait") == 1  # latched, not per-step
        # recovery: sustained on-budget steps clear the latch
        for _ in range(6):
            t = _emit_step(tr, t)
        aud.collect()
        assert aud.alarm_components() == []

    def test_warmup_window_never_alarms(self):
        tr, aud = _auditor(_budget())
        fired = []
        aud._on_alarm = lambda c, r, d: fired.append(c)
        t = 0
        for _ in range(WARMUP_STEPS):
            t = _emit_step(tr, t, data_wait_ms=50.0)
        aud.collect()
        assert fired == []

    def test_observed_seeded_budget_for_unpriced_component(self):
        # data_wait is not priced: its warmup mean becomes the budget
        b = _budget(data_wait_ms=0.0)
        tr, aud = _auditor(b)
        _run_warmup(tr, aud, data_wait_ms=8.0)
        assert aud.budget().data_wait_s == pytest.approx(0.008, rel=1e-6)
        assert aud.budget().source["data_wait"] == "observed"


class TestResizeNoDoubleCount:
    """The satellite regression test: spans spanning a mesh rebuild
    must not be double-counted into the next step's component
    buckets."""

    def test_straddling_span_clipped_per_window(self):
        # one compute span [0, 100ms) straddles two step windows:
        # step A [0, 60ms), step B [60ms, 120ms). Each bucket gets
        # only its inside portion — summed, never more than the span.
        tr, aud = _auditor(_budget())
        _put(tr, "compute", 0, 100 * MS, depth=1)
        _put(tr, "step", 0, 60 * MS, depth=0)
        _put(tr, "step", 60 * MS, 60 * MS, depth=0)
        res = aud.collect()
        assert len(res) == 2
        a, b = res
        assert a.observed["compute"] == pytest.approx(0.060)
        assert b.observed["compute"] == pytest.approx(0.040)
        total = a.observed["compute"] + b.observed["compute"]
        assert total == pytest.approx(0.100)

    def test_skip_to_now_drops_pre_resize_records(self):
        tr, aud = _auditor(_budget())
        t = _run_warmup(tr, aud)
        # records buffered but not collected when the resize lands
        _put(tr, "compute", t, 500 * MS, depth=1)
        _put(tr, "step", t, 505 * MS, depth=0)
        aud.skip_to_now()  # the resize boundary
        aud.set_budget(_budget(compute_ms=40.0))
        audited_before = aud.steps_audited
        assert aud.collect() == []  # old incarnation fully dropped
        t2 = t + 600 * MS
        for _ in range(WARMUP_STEPS + 1):
            t2 = _emit_step(tr, t2, compute_ms=40.0)
        res = aud.collect()
        assert aud.steps_audited == audited_before + WARMUP_STEPS + 1
        # the post-resize buckets hold only post-resize observation
        assert res[-1].observed["compute"] == pytest.approx(0.040)
        assert aud.alarm_components() == []

    def test_step_coverage_consistent_under_straddle(self):
        # the step_coverage acceptance number stays <= 1 when a child
        # leaks past its parent window (the rebuild-straddle shape):
        # the same clipping rule the auditor buckets use
        tr = SpanTracer(enabled=True)
        _put(tr, "compute", 0, 100 * MS, depth=1)
        _put(tr, "step", 0, 60 * MS, depth=0)
        _put(tr, "step", 60 * MS, 60 * MS, depth=0)
        cov = step_coverage(tr)
        assert cov is not None
        assert cov <= 1.0 + 1e-9


class TestCalibrationPersistence:
    def test_roundtrip_and_fingerprint_reject(self, tmp_path):
        cal = AuditCalibration(
            fingerprint="fp-a",
            factors={"compute": 1.3, "dcn_sync": 2.0},
            samples={"compute": 10, "dcn_sync": 4},
            updated_at=123.0,
        )
        path = save_audit_calibration(cal, dir_override=str(tmp_path))
        assert path and os.path.exists(path)
        back = load_audit_calibration("fp-a", dir_override=str(tmp_path))
        assert back.factors == pytest.approx(cal.factors)
        assert back.samples == cal.samples
        # a cache copied across worlds is rejected, not misapplied
        payload = json.load(open(path))
        payload["fingerprint"] = "fp-b"
        wrong = tmp_path / "auditcal-fp-c.json"
        wrong.write_text(json.dumps(payload))
        assert load_audit_calibration(
            "fp-c", dir_override=str(tmp_path)
        ) is None

    def test_auditor_persist_rate_limited(self, tmp_path):
        tr, aud = _auditor(_budget(compute_ms=50.0))
        t = 0
        for _ in range(WARMUP_STEPS + 5):
            t = _emit_step(tr, t, compute_ms=80.0)  # folds drift
        aud.collect()
        p1 = aud.persist("fp-x", dir_override=str(tmp_path))
        assert p1 is not None
        # no new samples + inside the min interval: both gates hold
        assert aud.persist("fp-x", dir_override=str(tmp_path)) is None
        assert aud.persist(
            "fp-x", dir_override=str(tmp_path), force=True
        ) is not None

    def test_apply_calibration_respects_live_samples(self):
        _tr, aud = _auditor()
        aud.seed_drift("compute", 1.4)  # live evidence
        cal = AuditCalibration(
            fingerprint="fp",
            factors={"compute": 9.0, "dcn_sync": 1.7},
            samples={"compute": 5, "dcn_sync": 5},
        )
        aud.apply_calibration(cal)
        f = aud.drift_factors()
        assert f["compute"] == pytest.approx(1.4)  # disk never outranks
        assert f["dcn_sync"] == pytest.approx(1.7)


class TestDefaultSeams:
    def test_seed_before_install_is_first_wins(self):
        seed_default_drift("compute", 2.0)
        seed_default_drift("compute", 9.0)
        assert current_drift_factors()["compute"] == pytest.approx(2.0)
        _tr, aud = _auditor()
        install_default_auditor(aud)
        # queued seeds transferred into the installed auditor
        assert aud.drift_factors()["compute"] == pytest.approx(2.0)
        assert current_drift_factors()["compute"] == pytest.approx(2.0)

    def test_current_factors_default_to_unity(self):
        f = current_drift_factors()
        assert set(f) == set(COMPONENTS)
        assert all(v == 1.0 for v in f.values())


class TestExportAndIngestion:
    def test_export_publishes_all_series(self):
        tr, aud = _auditor(_budget())
        _run_warmup(tr, aud)
        _emit_step(tr, 10_000 * MS)
        reg = MetricsRegistry()
        assert aud.export(reg) is not None
        scalars = reg.scalars()
        for series in (
            "residual_seconds", "observed_seconds", "budget_seconds",
            "drift_factor", "budget_ratio", "alarm",
        ):
            for c in COMPONENTS:
                key = (
                    f'dlrover_audit_{series}{{component="{c}"}}'
                )
                assert key in scalars, key
        assert scalars["dlrover_audit_steps_total"] == float(
            WARMUP_STEPS + 1
        )

    def test_aggregator_upgrades_straggler_why(self):
        from dlrover_tpu.obs.aggregate import TelemetryAggregator

        agg = TelemetryAggregator()
        agg.observe_metrics(3, 50, metrics={
            'dlrover_audit_budget_ratio{component="dcn_sync"}': 2.4,
            'dlrover_audit_budget_ratio{component="compute"}': 1.01,
            'dlrover_audit_alarm{component="dcn_sync"}': 1.0,
            'dlrover_audit_alarm{component="compute"}': 0.0,
        })
        why = agg.audit_attribution(3)
        assert "dcn_sync is 2.4x its budget" in why
        assert "compute" in why and "on-price" in why
        assert agg.audit_alarms() == {3: ["dcn_sync"]}
        assert agg.audit_attribution(99) == ""
        agg.remove_worker(3)
        assert agg.worker_audit(3) is None

    def test_brain_sink_carries_detail(self):
        from dlrover_tpu.brain.ingestion import straggler_sink
        from dlrover_tpu.brain.service import BrainServicer

        brain = BrainServicer(db_path=":memory:")
        report = straggler_sink(brain, "job-a")
        report(3, 0.5, 0.2, "dcn_sync is 2.4x its budget")
        rows = brain.node_events("job-a")
        assert rows and rows[0].event == "straggler"
        assert "dcn_sync" in rows[0].detail

    def test_merge_timeline_names_alarm_component(self):
        import sys

        sys.path.insert(
            0, os.path.join(os.path.dirname(__file__), "..", "tools")
        )
        try:
            from merge_timeline import merge_traces
        finally:
            sys.path.pop(0)
        trace = {
            "otherData": {"wall_t0_s": 100.0},
            "traceEvents": [{
                "ph": "X", "name": "step", "pid": 9, "tid": 1,
                "ts": 0, "dur": 5,
            }],
        }
        events = [{
            "ts": 100.5, "kind": "audit_regression",
            "detail": "dcn_sync observed 12.0ms vs budget 5.0ms "
            "(2.40x, source=priced)",
        }]
        merged = merge_traces([trace], ["w0"], events)
        markers = [
            e for e in merged["traceEvents"] if e.get("ph") == "i"
        ]
        assert markers[0]["name"] == "audit_regression:dcn_sync"
        assert markers[0]["args"]["component"] == "dcn_sync"


class TestDryRunnerRepricing:
    def test_reprice_report_per_component(self):
        from dlrover_tpu.accel.dry_runner import (
            DryRunReport,
            reprice_report,
        )

        r = DryRunReport(
            strategy=None,
            ok=True,
            est_step_s=1.0,
            comm_exposed_s=0.3,
            host_exposed_s=0.1,
            comm_ici_s=0.2,
            comm_dcn_s=0.1,
        )
        # compute share is 1.0 - 0.3 - 0.1 = 0.6
        out = reprice_report(r, {
            "compute": 1.0, "ici_sync": 1.0,
            "dcn_sync": 3.0, "host_xfer": 1.0,
        })
        assert out == pytest.approx(0.6 + 0.2 + 0.3 + 0.1)
        # only the drifted leg moved; a scalar calib would have
        # scaled all four
        out2 = reprice_report(r, {"compute": 2.0})
        assert out2 == pytest.approx(1.2 + 0.2 + 0.1 + 0.1)
