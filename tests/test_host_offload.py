"""Host-offloaded optimizer state (ops/host_offload.py — the
CPU-offload Adam analog): sharding metadata, numeric parity with the
on-device path, strategy plumbing, and the support gate.

Off TPU the feature is an explicit numeric no-op (the CPU backend
cannot execute placement annotations — module docstring), so on the
test backend these verify the full plumbing + parity; placement-kind
assertions are TPU-only.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.accel.opt_lib import apply_optimizations
from dlrover_tpu.accel.strategy import Strategy
from dlrover_tpu.models import build_train_step, init_sharded_state, tiny
from dlrover_tpu.models.train import state_shardings
from dlrover_tpu.ops.host_offload import (
    HOST_KIND,
    offload_tree,
    placement_active,
)
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

ON_TPU = jax.default_backend() == "tpu"


@pytest.fixture(scope="module")
def big_mesh():
    return build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))


@pytest.fixture(scope="module")
def cfg():
    return tiny(vocab_size=64, num_layers=2, max_seq_len=32)


def _tensor_kinds(tree):
    """Memory kinds of the tensor (ndim >= 1) leaves — scalars like the
    Adam step count deliberately stay device-resident."""
    return {
        x.sharding.memory_kind
        for x in jax.tree_util.tree_leaves(tree)
        if x.ndim
    }


class TestShardingMetadata:
    @pytest.mark.skipif(not ON_TPU, reason="placement is TPU-only")
    def test_opt_shardings_get_host_kind(self, cfg, big_mesh):
        from dlrover_tpu.models.transformer import init_params

        tx = optax.adamw(1e-3)
        sh = state_shardings(cfg, big_mesh, tx, offload_opt_state=True)
        params_shape = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), cfg)
        )
        opt_shape = jax.eval_shape(
            lambda: tx.init(
                jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), params_shape
                )
            )
        )
        kinds = {
            s.memory_kind
            for s, shape in zip(
                jax.tree_util.tree_leaves(sh.opt_state),
                jax.tree_util.tree_leaves(opt_shape),
            )
            if shape.ndim
        }
        assert kinds == {HOST_KIND}
        # params untouched
        assert HOST_KIND not in {
            s.memory_kind
            for s in jax.tree_util.tree_leaves(sh.params)
        }

    def test_offload_keeps_partitioning(self, cfg, big_mesh):
        tx = optax.adamw(1e-3)
        plain = state_shardings(cfg, big_mesh, tx)
        off = state_shardings(cfg, big_mesh, tx, offload_opt_state=True)
        specs = jax.tree_util.tree_map(
            lambda a, b: (a.spec == b.spec), plain.opt_state, off.opt_state
        )
        assert all(jax.tree_util.tree_leaves(specs))

    def test_offload_tree_roundtrip(self, cfg, big_mesh):
        # off TPU these are numeric no-ops; on TPU they place for real
        tx = optax.adamw(1e-3)
        state, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, big_mesh, tx
        )
        sh = state_shardings(cfg, big_mesh, tx, offload_opt_state=True)
        off = offload_tree(state.opt_state, sh.opt_state)
        if placement_active():
            assert _tensor_kinds(off) == {HOST_KIND}
        for a, b in zip(
            jax.tree_util.tree_leaves(state.opt_state),
            jax.tree_util.tree_leaves(off),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestSupportGate:
    def test_placement_active_matches_backend(self):
        assert placement_active() == ON_TPU


class TestParity:
    @pytest.mark.slow  # ~15s: offload parity double-compile; budget-gated out
    def test_step_matches_on_device_path(self, cfg, big_mesh):
        tx = optax.adamw(1e-3)
        mesh = big_mesh
        x = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (8, 32)), jnp.int32
        )
        state_a, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, mesh, tx
        )
        state_b, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, mesh, tx, offload_opt_state=True
        )
        step_a = build_train_step(cfg, mesh, tx, donate=False)
        step_b = build_train_step(
            cfg, mesh, tx, donate=False, offload_opt_state=True
        )
        sa, ma = step_a(state_a, x, x)
        sb, mb = step_b(state_b, x, x)
        np.testing.assert_allclose(
            float(ma["loss"]), float(mb["loss"]), rtol=1e-6
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(sa.params),
            jax.tree_util.tree_leaves(sb.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-7
            )
        for a, b in zip(
            jax.tree_util.tree_leaves(sa.opt_state),
            jax.tree_util.tree_leaves(sb.opt_state),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-7
            )
        if ON_TPU:  # in-jit placement only sticks on TPU
            assert _tensor_kinds(sb.opt_state) == {HOST_KIND}

    def test_composes_with_grad_accum(self, cfg, big_mesh):
        tx = optax.adamw(1e-3)
        x = jnp.asarray(
            np.random.default_rng(1).integers(0, 64, (8, 32)), jnp.int32
        )
        state, _ = init_sharded_state(
            jax.random.PRNGKey(1), cfg, big_mesh, tx,
            offload_opt_state=True,
        )
        step = build_train_step(
            cfg, big_mesh, tx, donate=True, grad_accum=4,
            offload_opt_state=True,
        )
        state, m = step(state, x, x)
        assert np.isfinite(float(m["loss"]))


class TestStrategyPlumbing:
    def test_opt_lib_entry(self):
        cfg = tiny()
        cfg2, s = apply_optimizations(cfg, Strategy(), ["offload_opt"])
        assert s.offload_opt
        assert "offload_opt" in s.opts
        assert "offload_opt" in s.describe()

    def test_strategy_json_roundtrip(self):
        s = Strategy(offload_opt=True)
        assert Strategy.from_json(s.to_json()).offload_opt

    def test_dry_runner_builds_offloaded_step(self, cfg):
        from dlrover_tpu.accel.dry_runner import _build

        s = Strategy(mesh=MeshConfig(dp=4, fsdp=2), offload_opt=True)
        tx = optax.adamw(1e-3)
        cfg2, mesh, step_fn, init_fn, make_batch, _ = _build(
            s, cfg, tx, jax.devices()
        )
        state = init_fn(jax.random.PRNGKey(0))
        x, y = make_batch(8, 32)
        state, m = step_fn(state, x, y)
        assert np.isfinite(float(m["loss"]))
