"""graftlint (ISSUE 15): the AST invariant-checker suite.

Three layers:

- **fixture tests** — for each of the six checkers, a synthetic
  violating snippet must produce exactly the expected finding id at
  the expected line (positive), and the correct pattern plus the
  suppression comment must both pass (negative);
- **tree-clean tier-1 gate** — the whole repo (``dlrover_tpu/`` +
  ``tools/``) must have ZERO unsuppressed findings, and every
  suppression must carry a reason. This is the test that keeps the
  mechanized review findings fixed forever;
- **real-violation regressions** — the concrete bugs the checkers
  caught in this tree (not the lint finding: the bug). The sharding
  client held its lock across master RPCs (lock-discipline.blocking),
  and the eviction drain leaked its goodput episode open on exception
  paths (span-leak).
"""

import os
import textwrap
import threading
import time

import pytest

from tools.graftlint import ALL_CHECKERS, Context, run_checkers
from tools.graftlint.checkers.durable_rename import DurableRenameChecker
from tools.graftlint.checkers.fault_sites import FaultSiteChecker
from tools.graftlint.checkers.locks import LockDisciplineChecker
from tools.graftlint.checkers.metrics_docs import MetricDocDriftChecker
from tools.graftlint.checkers.rpc import RpcIdempotencyChecker
from tools.graftlint.checkers.spans import SpanLeakChecker
from tools.graftlint.core import (
    discover_files,
    parse_suppressions,
    unsuppressed,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mini_repo(tmp_path, files):
    """Write ``{relpath: source}`` under ``tmp_path`` and build a
    Context over the .py files."""
    paths = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src).lstrip("\n"))
        if rel.endswith(".py"):
            paths.append(str(p))
    return Context(str(tmp_path), sorted(paths))


def run_one(checker, ctx):
    from tools.graftlint.core import apply_suppressions

    findings = apply_suppressions(ctx, checker.run(ctx))
    return findings


def live(findings):
    return [f for f in findings if not f.suppressed]


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------
class TestLockDiscipline:
    def test_positive_blocking_sleep_and_rpc(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import threading
                import time

                class C:
                    def __init__(self, client):
                        self._lock = threading.Lock()
                        self._client = client

                    def bad_sleep(self):
                        with self._lock:
                            time.sleep(1.0)

                    def bad_rpc(self):
                        with self._lock:
                            self._client.get_task("ds")
                """,
        })
        found = live(run_one(LockDisciplineChecker(), ctx))
        ids = {(f.checker, f.line) for f in found}
        assert ("lock-discipline.blocking", 11) in ids  # sleep
        assert ("lock-discipline.blocking", 15) in ids  # rpc

    def test_positive_cycle(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import threading

                class C:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def ab(self):
                        with self._a:
                            with self._b:
                                pass

                    def ba(self):
                        with self._b:
                            with self._a:
                                pass
                """,
        })
        found = live(run_one(LockDisciplineChecker(), ctx))
        cycles = [f for f in found if f.checker == "lock-discipline.cycle"]
        assert cycles and "mod:C._a" in cycles[0].message
        assert "mod:C._b" in cycles[0].message

    def test_positive_arbiter_leaf_rule(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import threading

                class C:
                    def __init__(self, stream):
                        self._lock = threading.Lock()
                        self._spill_stream = stream

                    def bad(self):
                        with self._lock:
                            with self._spill_stream.transfer(4096):
                                pass
                """,
        })
        found = live(run_one(LockDisciplineChecker(), ctx))
        assert any(
            f.checker == "lock-discipline.blocking"
            and "arbiter" in f.message
            for f in found
        )

    def test_positive_interprocedural_cycle(self, tmp_path):
        """The PR-14 ABBA shape: two classes, each taking its own lock
        then calling into the other (one level of call resolution)."""
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import threading

                class Store:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._arb = Arbiter(self)

                    def spill(self):
                        with self._lock:
                            self._arb.grant()

                    def fault_in(self):
                        with self._lock:
                            pass

                class Arbiter:
                    def __init__(self, store: "Store"):
                        self._cond = threading.Condition()
                        self._store = store

                    def grant(self):
                        with self._cond:
                            pass

                    def reap(self):
                        with self._cond:
                            self._store.fault_in()
                """,
        })
        found = live(run_one(LockDisciplineChecker(), ctx))
        cycles = [f for f in found if f.checker == "lock-discipline.cycle"]
        assert cycles, found
        assert "Store._lock" in cycles[0].message
        assert "Arbiter._cond" in cycles[0].message

    def test_negative_clean_and_suppressed(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import threading
                import time

                class C:
                    def __init__(self, client):
                        self._lock = threading.Lock()
                        self._cond = threading.Condition()
                        self._client = client
                        self._n = 0

                    def fine(self):
                        with self._lock:
                            self._n += 1
                        self._client.get_task("ds")  # outside: fine

                    def fine_cond_wait(self):
                        with self._cond:
                            self._cond.wait()  # releases the held lock

                    def fine_timed_wait(self, other):
                        with self._lock:
                            other.wait(timeout=1.0)

                    def deliberate(self):
                        with self._lock:
                            # graftlint: disable=lock-discipline.blocking reason=fixture
                            time.sleep(0.01)
                """,
        })
        findings = run_one(LockDisciplineChecker(), ctx)
        assert live(findings) == []
        assert any(f.suppressed for f in findings)

    def test_positive_wait_under_link_grant(self, tmp_path):
        """The device-tier wedge: joining the spill drain while HOLDING
        the fault-in link grant deadlocks — the drain needs the link to
        land its import. Both the direct shape and the one-level
        cross-function shape (the real bug: prepare -> _host_rows ->
        join_spills) must fire."""
        ctx = mini_repo(tmp_path, {
            "emb.py": """
                import time

                class Emb:
                    def prepare(self, missing):
                        with self._fault_stream.transfer(len(missing) * 4):
                            rows = self._host_rows(missing)
                        return rows

                    def _host_rows(self, missing):
                        self.join_spills()
                        return self.host.export_rows(missing)

                    def join_spills(self, timeout=30.0):
                        while True:
                            time.sleep(0.002)

                    def direct(self):
                        with self._spill_stream.transfer(64):
                            self.join_spills()
                """,
        })
        found = live(run_one(LockDisciplineChecker(), ctx))
        ids = {(f.checker, f.line) for f in found}
        assert ("lock-discipline.grant", 6) in ids  # via _host_rows
        assert ("lock-discipline.grant", 19) in ids  # direct

    def test_negative_join_before_grant(self, tmp_path):
        """The fixed pattern — join BEFORE acquiring the link grant —
        and a reasoned suppression both pass."""
        ctx = mini_repo(tmp_path, {
            "emb.py": """
                import time

                class Emb:
                    def prepare(self, missing):
                        self.join_spills()  # before the grant: fine
                        with self._fault_stream.transfer(len(missing) * 4):
                            rows = self.host.export_rows(missing)
                        return rows

                    def join_spills(self, timeout=30.0):
                        while True:
                            time.sleep(0.002)

                    def deliberate(self):
                        with self._spill_stream.transfer(64):
                            # graftlint: disable=lock-discipline.grant reason=fixture
                            self.join_spills()
                """,
        })
        findings = run_one(LockDisciplineChecker(), ctx)
        assert live(findings) == []
        assert any(f.suppressed for f in findings)

    def test_negative_nested_def_locks_not_attributed_to_method(
        self, tmp_path
    ):
        """Review caught phase 1 walking nested defs: a daemon-start
        method whose CLOSURE takes b-then-a must not hand the closure's
        locks to the method's summary — the caller holding `a` around
        `self.start()` would fabricate an a->b edge and a spurious
        cycle against the closure's own (real) b->a edge."""
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import threading

                class C:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def start(self):
                        def _loop():
                            with self._b:
                                with self._a:
                                    pass
                        return _loop

                    def under_a(self):
                        with self._a:
                            self.start()
                """,
        })
        found = live(run_one(LockDisciplineChecker(), ctx))
        assert [
            f for f in found if f.checker == "lock-discipline.cycle"
        ] == [], found


# ---------------------------------------------------------------------------
# span-leak
# ---------------------------------------------------------------------------
class TestSpanLeak:
    def test_positive_handle_never_closed(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                from obs import span

                def f(it):
                    sp = span("pull")
                    return next(it)
                """,
        })
        found = live(run_one(SpanLeakChecker(), ctx))
        assert [(f.checker, f.line) for f in found] == [("span-leak", 4)]

    def test_positive_handle_straightline_close(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                from obs import span

                def f(it):
                    sp = span("pull")
                    x = next(it)
                    sp.end()
                    return x
                """,
        })
        found = live(run_one(SpanLeakChecker(), ctx))
        assert [(f.checker, f.line) for f in found] == [("span-leak", 4)]
        assert "exception paths" in found[0].message

    def test_positive_episode_straightline_end(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                def drain(self):
                    self._goodput.eviction_begin()
                    self._emergency_save()
                    self._goodput.eviction_end()
                """,
        })
        found = live(run_one(SpanLeakChecker(), ctx))
        assert [(f.checker, f.line) for f in found] == [("span-leak", 2)]
        assert "eviction_begin" in found[0].message

    def test_negative_patterns(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                from obs import span

                def ctx_mgr(it):
                    with span("pull"):
                        return next(it)

                def try_finally(it):
                    sp = span("pull")
                    try:
                        return next(it)
                    finally:
                        sp.end()

                def cancel_on_raise(it):
                    sp = span("step")
                    try:
                        x = next(it)
                        sp.end()
                        return x
                    except BaseException:
                        sp.cancel()
                        raise

                def episode_finally(self):
                    self._goodput.eviction_begin()
                    try:
                        self._emergency_save()
                    finally:
                        self._goodput.eviction_end()

                def dispatch_helper(ledger, entered):
                    if entered:
                        ledger.degraded_enter()
                    else:
                        ledger.degraded_exit()

                def cross_function_begin(self):
                    self._goodput.replay_begin()

                def escaping_handle(tracer):
                    sp = tracer.span("outer")
                    return sp
                """,
        })
        assert live(run_one(SpanLeakChecker(), ctx)) == []

    def test_positive_narrow_except_is_not_safe(self, tmp_path):
        """A close only inside `except ValueError` leaks every other
        exception — the handler must be bare/Exception/BaseException."""
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                from obs import span

                def f(it):
                    sp = span("pull")
                    try:
                        x = next(it)
                        sp.end()
                        return x
                    except ValueError:
                        sp.cancel()
                        raise
                """,
        })
        found = live(run_one(SpanLeakChecker(), ctx))
        assert [(f.checker, f.line) for f in found] == [("span-leak", 4)]

    def test_negative_suppressed(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                from obs import span

                def f(it):
                    # graftlint: disable=span-leak reason=fixture
                    sp = span("pull")
                    return next(it)
                """,
        })
        findings = run_one(SpanLeakChecker(), ctx)
        assert live(findings) == []
        assert any(f.suppressed for f in findings)


# ---------------------------------------------------------------------------
# rpc-idempotency
# ---------------------------------------------------------------------------
_MINI_COMM = """
    from dataclasses import dataclass, field

    class Message:
        pass

    @dataclass
    class BaseRequest(Message):
        data: bytes = b""

    @dataclass
    class BaseResponse(Message):
        success: bool = True

    @dataclass
    class PingRequest(Message):
        n: int = 0

    @dataclass
    class Pong(Message):
        n: int = 0

    @dataclass
    class OrphanRequest(Message):
        n: int = 0

    @dataclass
    class DeadArm(Message):
        n: int = 0

    @dataclass
    class KeyValueAdd(Message):
        key: str = ""
        amount: int = 0
    """

_MINI_SERVICER = """
    from dlrover_tpu.common import comm

    class Servicer:
        def _dispatch_get(self, message):
            if isinstance(message, comm.PingRequest):
                return comm.Pong(n=message.n)
            if isinstance(message, comm.DeadArm):
                return None
            raise ValueError("unknown")

        def _dispatch_report(self, message):
            if isinstance(message, comm.KeyValueAdd):
                return True
            raise ValueError("unknown")
    """


class TestRpcIdempotency:
    def _ctx(self, tmp_path, client_src):
        return mini_repo(tmp_path, {
            "dlrover_tpu/common/comm.py": _MINI_COMM,
            "dlrover_tpu/master/servicer.py": _MINI_SERVICER,
            "dlrover_tpu/agent/master_client.py": client_src,
        })

    def test_positive_matrix_and_retry(self, tmp_path):
        ctx = self._ctx(tmp_path, """
            from dlrover_tpu.common import comm

            class MasterClient:
                def ping(self):
                    return self.get(comm.PingRequest(n=1))

                def orphan(self):
                    return self.report(comm.OrphanRequest(n=1))

                def bad_add(self):
                    return self.report(comm.KeyValueAdd(key="k", amount=1))
            """)
        found = live(run_one(RpcIdempotencyChecker(), ctx))
        by_id = {}
        for f in found:
            by_id.setdefault(f.checker, []).append(f)
        # OrphanRequest: sent, no dispatch arm
        assert any(
            "OrphanRequest" in f.message
            for f in by_id.get("rpc-idempotency.dispatch", [])
        )
        # DeadArm: dispatched, never constructed
        assert any(
            "DeadArm" in f.message and "dead arm" in f.message
            for f in by_id.get("rpc-idempotency.dispatch", [])
        )
        # KeyValueAdd retried without idempotent=False
        assert any(
            "KeyValueAdd" in f.message
            for f in by_id.get("rpc-idempotency.retry", [])
        )

    def test_positive_variable_passed_send(self, tmp_path):
        """A message passed as a VARIABLE (`self.report(params)`) still
        counts as sent — resolved through the parameter annotation."""
        ctx = self._ctx(tmp_path, """
            from dlrover_tpu.common import comm

            class MasterClient:
                def send_orphan(self, params: comm.OrphanRequest):
                    return self.report(params)
            """)
        found = live(run_one(RpcIdempotencyChecker(), ctx))
        assert any(
            f.checker == "rpc-idempotency.dispatch"
            and "OrphanRequest" in f.message
            for f in found
        ), found

    def test_negative_variable_passed_send_covers_arm(self, tmp_path):
        """A local `x = comm.DeadArm(...)` later sent keeps the arm
        alive through one level of assignment resolution."""
        ctx = self._ctx(tmp_path, """
            from dlrover_tpu.common import comm

            class MasterClient:
                def ping(self):
                    return self.get(comm.PingRequest(n=1))

                def send_dead(self):
                    msg = comm.DeadArm(n=1)
                    return self.get(msg)

                def good_add(self):
                    return self.report(
                        comm.KeyValueAdd(key="k", amount=1), retries=1
                    )

                def orphan_local(self):
                    return comm.OrphanRequest(n=1)
            """)
        assert live(run_one(RpcIdempotencyChecker(), ctx)) == []

    def test_negative_covered_matrix(self, tmp_path):
        ctx = self._ctx(tmp_path, """
            from dlrover_tpu.common import comm

            class MasterClient:
                def ping(self):
                    return self.get(comm.PingRequest(n=1))

                def dead(self):
                    return self.get(comm.DeadArm(n=1))

                def orphan_local(self):
                    # constructed but never sent: not a matrix hole
                    return comm.OrphanRequest(n=1)

                def good_add(self):
                    return self.report(
                        comm.KeyValueAdd(key="k", amount=1),
                        idempotent=False,
                    )
            """)
        assert live(run_one(RpcIdempotencyChecker(), ctx)) == []


# ---------------------------------------------------------------------------
# metric-doc-drift
# ---------------------------------------------------------------------------
class TestMetricDocDrift:
    def test_positive_both_directions(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "docs/observability.md": """
                | name | type | meaning |
                |---|---|---|
                | `dlrover_good_total` | counter | fine |
                | `dlrover_stale_gone` | gauge | no longer in code |
                """,
            "mod.py": """
                def export(reg):
                    reg.counter("dlrover_good_total", "fine").inc()
                    reg.gauge("dlrover_undocumented", "oops").set(1.0)
                """,
        })
        found = live(run_one(MetricDocDriftChecker(), ctx))
        msgs = [f.message for f in found]
        assert any("dlrover_undocumented" in m for m in msgs)
        assert any("dlrover_stale_gone" in m for m in msgs)
        assert all(f.checker == "metric-doc-drift" for f in found)
        # the stale row is flagged AT the doc file
        stale = [f for f in found if "stale" in f.message or "not constructed" in f.message]
        assert stale and stale[0].path.endswith("observability.md")

    def test_negative_prefix_families_and_dynamic(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "docs/observability.md": """
                | name | type | meaning |
                |---|---|---|
                | `dlrover_fam_<field>` | gauge | a family |
                | `dlrover_labeled_total{site,kind}` | counter | labels stripped |
                """,
            "mod.py": """
                PREFIX = "dlrover_fam_"

                def export(reg, k):
                    reg.gauge(f"dlrover_fam_{k}", "one of the family").set(1.0)
                    reg.gauge(PREFIX + k, "same family").set(1.0)
                    reg.counter("dlrover_labeled_total", "x", ("site", "kind"))
                """,
        })
        assert live(run_one(MetricDocDriftChecker(), ctx)) == []


# ---------------------------------------------------------------------------
# fault-site
# ---------------------------------------------------------------------------
class TestFaultSite:
    def test_positive_all_three_rules(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "dlrover_tpu/common/faults.py": """
                FAULT_SITES = frozenset(
                    {
                        "a.fired_tested",
                        "c.never_fired",
                        "e.fired_untested",
                    }
                )
                """,
            "dlrover_tpu/prod.py": """
                from dlrover_tpu.common import faults

                def work():
                    faults.fire("a.fired_tested")
                    faults.fire("e.fired_untested")
                    faults.fire("zz.unregistered")
                """,
            "tests/test_chaos.py": """
                SPEC = "a.fired_tested:enospc:1.0;c.never_fired:delay:0.5"
                """,
        })
        found = live(run_one(FaultSiteChecker(), ctx))
        msgs = "\n".join(f"{f.line}:{f.message}" for f in found)
        assert "zz.unregistered" in msgs and "never be armed" in msgs
        assert "c.never_fired" in msgs and "never fired" in msgs
        assert "e.fired_untested" in msgs and "any test" in msgs
        # exactly those three rules fired, nothing else
        assert len(found) == 3, msgs

    def test_negative_clean_registry(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "dlrover_tpu/common/faults.py": """
                FAULT_SITES = frozenset({"a.b"})
                """,
            "dlrover_tpu/prod.py": """
                from dlrover_tpu.common import faults

                def work(blob):
                    faults.fire("a.b")
                    return faults.corrupt("a.b", blob)
                """,
            "tests/test_chaos.py": """
                SPEC = "a.b:torn_write:1.0"
                """,
        })
        assert live(run_one(FaultSiteChecker(), ctx)) == []


# ---------------------------------------------------------------------------
# durable-rename
# ---------------------------------------------------------------------------
class TestDurableRename:
    def test_positive_write_then_rename_no_fsync(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import json
                import os

                def save(state, path):
                    tmp = path + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump(state, f)
                    os.replace(tmp, path)
                """,
        })
        found = live(run_one(DurableRenameChecker(), ctx))
        assert [(f.checker, f.line) for f in found] == [
            ("durable-rename", 8)
        ]

    def test_negative_fsync_renameonly_suppressed(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import json
                import os

                def save_durable(state, path):
                    tmp = path + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump(state, f)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, path)

                def quarantine(path):
                    # rename-only move: nothing written here
                    os.replace(path, path + ".corrupt")

                def read_only(path):
                    with open(path) as f:
                        data = f.read()
                    os.replace(path, path + ".seen")
                    return data

                def fdopen_read_then_move(fd, path):
                    # os.fdopen with no mode defaults to READ — not a
                    # write, so the rename needs no fsync
                    with os.fdopen(fd) as f:
                        data = f.read()
                    os.replace(path, path + ".seen")
                    return data

                def telemetry(payload, path):
                    tmp = path + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump(payload, f)
                    # graftlint: disable=durable-rename reason=fixture
                    os.replace(tmp, path)
                """,
        })
        findings = run_one(DurableRenameChecker(), ctx)
        assert live(findings) == []
        assert any(f.suppressed for f in findings)


# ---------------------------------------------------------------------------
# audit-budget-coverage
# ---------------------------------------------------------------------------
_AUDIT_FIXTURE_OK = {
    "dlrover_tpu/obs/audit.py": """
        COMPONENTS = ("compute", "data_wait")

        OBSERVED = {
            "compute": ("compute",),
            "data_wait": ("data_wait",),
        }

        class StepBudget:
            compute_s: float = 0.0
            data_wait_s: float = 0.0
        """,
    "dlrover_tpu/trainer.py": """
        from dlrover_tpu.obs.trace import span

        def loop():
            with span("data_wait"):
                pass
            with span("compute"):
                pass
        """,
}


class TestAuditBudgetCoverage:
    def test_negative_aligned_vocabularies(self, tmp_path):
        from tools.graftlint.checkers.audit_budget import (
            AuditBudgetCoverageChecker,
        )

        ctx = mini_repo(tmp_path, dict(_AUDIT_FIXTURE_OK))
        assert live(run_one(AuditBudgetCoverageChecker(), ctx)) == []

    def test_positive_all_rules(self, tmp_path):
        from tools.graftlint.checkers.audit_budget import (
            AuditBudgetCoverageChecker,
        )

        ctx = mini_repo(tmp_path, {
            # priced_only: no budget field, no OBSERVED entry;
            # ghost: OBSERVED span nothing emits; stale_field /
            # stale_key: budget field / OBSERVED key not in COMPONENTS
            "dlrover_tpu/obs/audit.py": """
                COMPONENTS = ("compute", "priced_only", "ghost")

                OBSERVED = {
                    "compute": ("compute",),
                    "ghost": ("never_emitted",),
                    "stale_key": ("compute",),
                }

                class StepBudget:
                    compute_s: float = 0.0
                    ghost_s: float = 0.0
                    stale_field_s: float = 0.0
                """,
            "dlrover_tpu/trainer.py": """
                from dlrover_tpu.obs.trace import span

                def loop():
                    with span("compute"):
                        pass
                """,
        })
        found = live(run_one(AuditBudgetCoverageChecker(), ctx))
        msgs = "\n".join(f"{f.line}:{f.message}" for f in found)
        assert "'priced_only'" in msgs and "never be priced" in msgs
        assert "reconciles against nothing" in msgs
        assert "'never_emitted'" in msgs and "never emitted" in msgs
        assert "stale_field" in msgs and "never audited" in msgs
        assert "'stale_key'" in msgs and "stale registry" in msgs
        assert len(found) == 5, msgs

    def test_span_emitted_in_tests_does_not_count(self, tmp_path):
        from tools.graftlint.checkers.audit_budget import (
            AuditBudgetCoverageChecker,
        )

        files = dict(_AUDIT_FIXTURE_OK)
        # move the data_wait emission into a test file: production
        # never emits it, so the auditor measures zero forever
        files["dlrover_tpu/trainer.py"] = """
            from dlrover_tpu.obs.trace import span

            def loop():
                with span("compute"):
                    pass
            """
        files["tests/test_x.py"] = """
            from dlrover_tpu.obs.trace import span

            def test_loop():
                with span("data_wait"):
                    pass
            """
        ctx = mini_repo(tmp_path, files)
        found = live(run_one(AuditBudgetCoverageChecker(), ctx))
        assert len(found) == 1
        assert "'data_wait'" in found[0].message

    def test_real_tree_vocabularies_parse(self):
        """The checker must statically read all three views from the
        real obs/audit.py (an unparseable vocabulary is itself a
        finding, by design)."""
        import ast as _ast

        from tools.graftlint.checkers.audit_budget import (
            AuditBudgetCoverageChecker,
        )

        path = os.path.join(REPO_ROOT, "dlrover_tpu/obs/audit.py")
        tree = _ast.parse(open(path).read())
        chk = AuditBudgetCoverageChecker()
        comps = chk._components(tree)
        obs = chk._observed(tree)
        fields = chk._budget_fields(tree)
        assert comps is not None and obs is not None
        assert fields is not None
        assert comps[0] == fields[0] == set(obs[0])


# ---------------------------------------------------------------------------
# suppression machinery
# ---------------------------------------------------------------------------
class TestSuppressions:
    def test_reasonless_suppression_is_a_finding(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import time

                def f():
                    # graftlint: disable=lock-discipline.blocking
                    time.sleep(0.01)
                """,
        })
        findings = run_checkers(ctx, ALL_CHECKERS)
        bad = [f for f in findings if f.checker == "graftlint.suppression"]
        assert bad and "without a reason" in bad[0].message
        assert not bad[0].suppressed

    def test_parse_grammar(self):
        by_line, bad = parse_suppressions([
            "x = 1  # graftlint: disable=span-leak reason=ok here",
            "# graftlint: disable=a,b reason=two ids",
            "y = 2",
            "z = 3  # graftlint: disable=durable-rename",
        ])
        assert by_line[1].ids == ("span-leak",)
        assert by_line[1].reason == "ok here"
        assert by_line[3].ids == ("a", "b")  # own-line: next line
        assert len(bad) == 1 and bad[0].raw_line == 4

    def test_trailing_suppression_does_not_leak_to_next_line(self, tmp_path):
        """Review caught the line-above probe: a trailing suppression
        on line N must suppress ONLY line N's finding — the next
        statement's independent violation stays live (it would
        otherwise pass the zero-unsuppressed gate wearing its
        neighbor's reason)."""
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import threading
                import time

                class C:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def f(self):
                        with self._lock:
                            time.sleep(0.01)  # graftlint: disable=lock-discipline.blocking reason=fixture
                            time.sleep(0.02)
                """,
        })
        findings = run_one(LockDisciplineChecker(), ctx)
        assert [f.line for f in live(findings)] == [11]
        assert [f.line for f in findings if f.suppressed] == [10]

    def test_parent_id_suppresses_sub_id(self, tmp_path):
        ctx = mini_repo(tmp_path, {
            "mod.py": """
                import threading
                import time

                class C:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def f(self):
                        with self._lock:
                            # graftlint: disable=lock-discipline reason=fixture
                            time.sleep(0.01)
                """,
        })
        findings = run_one(LockDisciplineChecker(), ctx)
        assert live(findings) == []


# ---------------------------------------------------------------------------
# the tier-1 gate: the real tree is clean
# ---------------------------------------------------------------------------
class TestTreeClean:
    def test_repo_has_zero_unsuppressed_findings(self):
        """The whole point: dlrover_tpu/ + tools/ stay graftlint-clean.
        A finding here is either a real bug (fix it) or a deliberate
        pattern (suppress WITH a reason at the site)."""
        files = discover_files(REPO_ROOT, ["dlrover_tpu", "tools"])
        ctx = Context(REPO_ROOT, files)
        findings = run_checkers(ctx, ALL_CHECKERS)
        livef = unsuppressed(findings)
        assert livef == [], "\n" + "\n".join(f.render() for f in livef)

    def test_repo_suppressions_all_carry_reasons(self):
        files = discover_files(REPO_ROOT, ["dlrover_tpu", "tools"])
        ctx = Context(REPO_ROOT, files)
        for path in files:
            _, bad = parse_suppressions(ctx.lines(path))
            assert not bad, f"reasonless suppression in {ctx.rel(path)}"

    def test_cli_json_and_exit_zero(self, capsys):
        """One cheap checker keeps this a CLI-shape test — the full
        tree-clean pass above is the expensive gate, once."""
        import json as _json

        from tools.graftlint.__main__ import main

        rc = main([
            "--json", "--select", "durable-rename", "--root", REPO_ROOT,
        ])
        out = capsys.readouterr().out
        payload = _json.loads(out)
        assert rc == 0
        assert payload["unsuppressed"] == 0
        assert payload["suppressed"] >= 1  # the deliberate ones exist

    def test_cli_select_and_list(self, capsys):
        from tools.graftlint.__main__ import main

        assert main(["--list-checkers"]) == 0
        out = capsys.readouterr().out
        assert "lock-discipline" in out and "durable-rename" in out
        rc = main(["--select", "span-leak", "--root", REPO_ROOT])
        assert rc == 0
        rc = main(["--select", "not-a-checker", "--root", REPO_ROOT])
        assert rc == 2

    def test_cli_changed_only(self):
        from tools.graftlint.__main__ import main

        # per-file checkers restricted to the git diff; on a clean
        # tree both paths exit 0
        assert main([
            "--changed-only", "--select", "durable-rename",
            "--root", REPO_ROOT,
        ]) == 0

    def test_cli_subtree_keeps_repo_scope_whole_tree(self, capsys):
        """Review caught subtree operands starving the repo-scope
        checkers: `graftlint dlrover_tpu/ckpt` must NOT compare
        docs/observability.md or comm.py against the subtree's few
        files (it reported 54 false findings). On a clean tree the
        subtree run exits 0."""
        from tools.graftlint.__main__ import main

        assert main(["dlrover_tpu/ckpt", "--root", REPO_ROOT]) == 0
        capsys.readouterr()

    def test_cli_bad_path_is_a_usage_error(self, capsys):
        """A typo'd path operand must exit 2, not pass vacuously —
        a pre-PR gate that silently lints nothing is the exact
        silent-fallback class the suite exists to catch."""
        from tools.graftlint.__main__ import main

        assert main(["dlrover_tpu/ckppt", "--root", REPO_ROOT]) == 2
        assert "no such path" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# real-violation regressions (the bug, not the lint finding)
# ---------------------------------------------------------------------------
class TestShardingClientLockRegression:
    """lock-discipline.blocking caught IndexShardingClient holding
    self._lock across MasterClient RPCs (_fill's get_task and
    report_batch_done's report_task_result): a master brownout then
    stalled the training thread's shard-ack path on the lock for up to
    the 60 s retry budget. The fix moves both RPCs outside the lock —
    these tests assert the lock is FREE while each RPC is in flight."""

    def _client(self):
        from dlrover_tpu.agent.sharding_client import IndexShardingClient
        from dlrover_tpu.common import comm

        class StubMaster:
            def __init__(self):
                self.owner = None
                self.lock_free_during_get = None
                self.lock_free_during_report = None
                self.reported = []
                self._served = 0

            def _lock_free(self):
                ok = self.owner._lock.acquire(blocking=False)
                if ok:
                    self.owner._lock.release()
                return ok

            def report_dataset_shard_params(self, params):
                return True

            def get_task(self, name):
                self.lock_free_during_get = self._lock_free()
                self._served += 1
                if self._served == 1:
                    return comm.Task(
                        task_id=7,
                        task_type="train",
                        shard=comm.Shard(name=name, start=0, end=4),
                    )
                return comm.Task()  # empty: exhausted

            def report_task_result(self, name, task_id):
                self.lock_free_during_report = self._lock_free()
                self.reported.append(task_id)
                return True

        stub = StubMaster()
        client = IndexShardingClient(
            stub, "ds", batch_size=2, dataset_size=4
        )
        stub.owner = client
        return client, stub

    def test_fill_rpc_runs_outside_the_lock(self):
        client, stub = self._client()
        client._fill()
        assert stub.lock_free_during_get is True
        # the shard's indices landed atomically
        assert [client._index_queue.get_nowait() for _ in range(4)] == [
            0, 1, 2, 3,
        ]

    def test_ack_rpc_runs_outside_the_lock(self):
        client, stub = self._client()
        client._fill()
        client.report_batch_done(4)  # full shard consumed -> ack RPC
        assert stub.reported == [7]
        assert stub.lock_free_during_report is True

    def test_brownout_does_not_starve_the_ack_path(self):
        """The end-to-end symptom: with a WEDGED get_task in flight,
        report_batch_done must complete immediately instead of queueing
        behind the brownout."""
        client, stub = self._client()
        client._fill()  # one pending shard to ack

        release = threading.Event()
        in_rpc = threading.Event()
        real_get = stub.get_task

        def wedged_get(name):
            in_rpc.set()
            assert release.wait(5.0), "test wedge never released"
            return real_get(name)

        stub.get_task = wedged_get
        filler = threading.Thread(target=client._fill, daemon=True)
        filler.start()
        assert in_rpc.wait(5.0)
        t0 = time.perf_counter()
        client.report_batch_done(4)  # must NOT wait for the brownout
        elapsed = time.perf_counter() - t0
        release.set()
        filler.join(5.0)
        assert stub.reported == [7]
        assert elapsed < 1.0, (
            f"ack path blocked {elapsed:.1f}s behind a wedged fill RPC"
        )

    def test_one_failing_ack_does_not_drop_the_rest_of_the_batch(self):
        """Moving the acks outside the lock batched them into one loop;
        review caught that an RPC failure mid-loop then lost the acks
        of every OTHER already-popped task (the pre-batching code lost
        at most the one failing shard — and even that one only until
        node death). The acks must be independent AND retryable: the
        failure propagates, the remaining tasks still ack, and the
        failed task re-queues with its credit restored so the next
        call retries it."""
        from dlrover_tpu.agent.sharding_client import IndexShardingClient
        from dlrover_tpu.common import comm

        class StubMaster:
            def __init__(self):
                self.reported = []
                self.fail_ids = {1}
                self._served = 0

            def report_dataset_shard_params(self, params):
                return True

            def get_task(self, name):
                self._served += 1
                if self._served <= 3:
                    s = (self._served - 1) * 2
                    return comm.Task(
                        task_id=self._served,
                        task_type="train",
                        shard=comm.Shard(name=name, start=s, end=s + 2),
                    )
                return comm.Task()  # empty: exhausted

            def report_task_result(self, name, task_id):
                if task_id in self.fail_ids:
                    raise ConnectionError("brownout on the first ack")
                self.reported.append(task_id)
                return True

        stub = StubMaster()
        client = IndexShardingClient(stub, "ds", batch_size=2, dataset_size=6)
        for _ in range(3):
            client._fill()  # three pending 2-record shards
        with pytest.raises(ConnectionError):
            client.report_batch_done(6)  # all three fully consumed
        # tasks 2 and 3 were popped alongside the failing task 1 —
        # their acks must have gone out anyway
        assert stub.reported == [2, 3]
        # task 1 re-queued with its credit restored: the master comes
        # back, and the NEXT report retries (and drains) it
        stub.fail_ids = set()
        client.report_batch_done(0)
        assert stub.reported == [2, 3, 1]
        assert client._pending_tasks.empty()
        assert client._uncredited == 0


class TestEvictionEpisodeLeakRegression:
    """span-leak caught _drain_for_eviction booking the eviction episode
    open with eviction_end() only on the straight-line path: an
    exception escaping the drain (a failed prefetcher close, a full
    disk in the announce write) left the episode open FOREVER and the
    goodput ledger then attributed every later second to `eviction`.
    The fix closes the episode in a finally; this reproduces the bug's
    trigger and asserts the ledger closes."""

    def _trainer(self, tmp_path):
        import jax
        import optax
        import numpy as np

        from dlrover_tpu.accel.strategy import Strategy
        from dlrover_tpu.models.config import tiny
        from dlrover_tpu.parallel.mesh import MeshConfig
        from dlrover_tpu.trainer.elastic.trainer import (
            ElasticTrainer,
            TrainerConfig,
        )

        class _Tokens:
            def __init__(self, n=64, seq=16, vocab=64):
                rng = np.random.default_rng(5)
                self.data = rng.integers(
                    0, vocab, (n, seq + 1), dtype=np.int32
                )

            def __len__(self):
                return len(self.data)

            def __getitem__(self, i):
                return {"x": self.data[i][:-1], "y": self.data[i][1:]}

        return ElasticTrainer(
            model_cfg=tiny(num_layers=1),
            tx=optax.adamw(1e-2),
            dataset=_Tokens(),
            trainer_cfg=TrainerConfig(
                batch_size=4,
                seq_len=16,
                ckpt_dir=str(tmp_path / "ckpt"),
                report_metrics=False,
                prefetch=0,
                donation_aware=False,
                speculative_compile=False,
                eviction_grace_s=5.0,
            ),
            strategy=Strategy(mesh=MeshConfig(dp=1), dtype="float32"),
            devices=list(__import__("jax").devices())[:1],
        )

    def test_failed_drain_still_closes_the_episode(self, tmp_path):
        trainer = self._trainer(tmp_path)
        try:
            boom = RuntimeError("prefetcher close exploded")

            def exploding_close():
                raise boom

            trainer._close_prefetcher = exploding_close
            with pytest.raises(RuntimeError, match="exploded"):
                trainer._drain_for_eviction()
            # the bug: _eviction_since stayed set and the ledger booked
            # everything after as `eviction`
            assert trainer._goodput._eviction_since is None
            assert trainer.evicted is True
            assert trainer.eviction_drain_ms > 0.0
            # and the booked episode stops growing once the drain died
            s0 = trainer._goodput.snapshot().seconds["eviction"]
            time.sleep(0.05)
            s1 = trainer._goodput.snapshot().seconds["eviction"]
            assert s1 == pytest.approx(s0, abs=1e-3)
        finally:
            trainer._flight.clear_suppression()
            trainer._close_prefetcher = lambda: None
            trainer.close()


class TestBrainPersistIdempotencyRegression:
    """rpc-idempotency flagged the retried BrainMetricsReport leg over
    a blind INSERT: a lost response double-inserted the sample on
    replay. The guarded insert makes the replay a no-op."""

    def test_replayed_sample_inserts_once(self):
        from dlrover_tpu.brain.service import BrainServicer
        from dlrover_tpu.common import comm

        svc = BrainServicer(":memory:")
        s = comm.JobMetricsSample(
            timestamp=123.5, global_step=10, steps_per_sec=2.0,
            alive_nodes=4,
        )
        svc.persist_metrics("job", s)
        svc.persist_metrics("job", s)  # the client retry's replay
        rows = svc.job_metrics("job", 0)
        assert len(rows) == 1
        # a genuinely new sample still lands
        s2 = comm.JobMetricsSample(
            timestamp=124.5, global_step=11, steps_per_sec=2.0,
            alive_nodes=4,
        )
        svc.persist_metrics("job", s2)
        assert len(svc.job_metrics("job", 0)) == 2


class TestRpcMatrixCompletions:
    """The dead dispatch arms the checker found (ElasticRunConfigRequest,
    NodeEventReport, SyncFinishRequest had servicer arms no client could
    send) — the new client methods must round-trip through the real
    dispatch."""

    def _pair(self):
        from dlrover_tpu.master.servicer import MasterServicer

        class LoopbackClient:
            """MasterClient wire semantics against an in-proc servicer."""

            def __init__(self, servicer, node_id=3, node_type="worker"):
                from dlrover_tpu.agent.master_client import MasterClient
                from dlrover_tpu.common import comm as _comm

                self._mc = MasterClient.__new__(MasterClient)
                self._mc._node_id = node_id
                self._mc._node_type = node_type
                self._servicer = servicer
                self._comm = _comm
                self._mc.get = self._get
                self._mc.report = self._report

            def _get(self, message, **kw):
                from dlrover_tpu.common import comm

                wrapped = self._mc._wrap(message)
                resp = comm.deserialize_message(
                    self._servicer.get(wrapped)
                )
                assert resp.success, resp.message
                return comm.deserialize_message(resp.data)

            def _report(self, message, **kw):
                from dlrover_tpu.common import comm

                wrapped = self._mc._wrap(message)
                resp = comm.deserialize_message(
                    self._servicer.report(wrapped)
                )
                assert resp.success, resp.message
                return comm.deserialize_message(resp.data)

        class _JobManager:
            def __init__(self):
                self.events = []

            def process_event(self, ev):
                self.events.append(ev)

        jm = _JobManager()

        class _Sync:
            def __init__(self):
                self.finished = []

            def finish_sync(self, name):
                self.finished.append(name)

            def sync_finished(self, name):
                return name in self.finished

        sync = _Sync()
        servicer = MasterServicer(job_manager=jm, sync_service=sync)
        servicer._run_configs = {"flagged": "on"}
        return servicer, LoopbackClient(servicer), jm, sync

    def test_get_elastic_run_config(self):
        _, lb, _, _ = self._pair()
        assert lb._mc.get_elastic_run_config() == {"flagged": "on"}

    def test_report_node_event(self):
        _, lb, jm, _ = self._pair()
        lb._mc.report_node_event("ADDED", message="hello")
        assert len(jm.events) == 1
        assert jm.events[0].node.id == 3

    def test_finish_sync(self):
        _, lb, _, sync = self._pair()
        assert lb._mc.finish_sync("warmup") is True
        assert sync.finished == ["warmup"]
        assert lb._mc.sync_finished("warmup") is True
