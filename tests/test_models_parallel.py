"""Models + parallelism tests on the virtual 8-device CPU mesh.

The key invariant everywhere: sharded execution (any mesh) must be
numerically equal to single-device execution — GSPMD/ring/all-to-all are
layout changes, not math changes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import (
    TrainState,
    build_train_step,
    init_params,
    init_sharded_state,
    logical_axes,
    forward,
    shard_batch,
    tiny,
)
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.ring_attention import ring_self_attention
from dlrover_tpu.parallel.moe import init_moe_params, moe_layer
from dlrover_tpu.parallel.sharding_rules import default_lm_rules


def _tokens(B=8, T=64, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (B, T)).astype(np.int32)


def _dense_attention_ref(q, k, v, causal=True, mask=None):
    """The one dense-attention oracle both SP schemes are tested
    against (mask: [S,S] bool overrides causal)."""
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D**-0.5)
    if mask is None and causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
    if mask is not None:
        s = jnp.where(mask[None, None], s, -jnp.inf)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)


class TestRingAttention:
    def _ref(self, q, k, v, causal):
        return _dense_attention_ref(q, k, v, causal=causal)

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        mesh = build_mesh(MeshConfig(dp=2, sp=4))
        B, S, H, D = 4, 32, 4, 8
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
        out = ring_self_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(
            out, self._ref(q, k, v, causal), atol=2e-5
        )

    def test_gqa_and_tp(self):
        mesh = build_mesh(MeshConfig(sp=4, tp=2))
        B, S, H, Hkv, D = 2, 32, 4, 2, 8
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(ks[0], (B, S, H, D))
        k = jax.random.normal(ks[1], (B, S, Hkv, D))
        v = jax.random.normal(ks[2], (B, S, Hkv, D))
        out = ring_self_attention(q, k, v, mesh, causal=True)
        kr = jnp.repeat(k, 2, axis=2)
        vr = jnp.repeat(v, 2, axis=2)
        np.testing.assert_allclose(
            out, self._ref(q, kr, vr, True), atol=2e-5
        )

    def test_custom_mask(self):
        # bidirectional prefix of 16 + causal tail (GLM-style)
        mesh = build_mesh(MeshConfig(dp=2, sp=4))

        def mask_fn(q_pos, k_pos):
            causal = q_pos[:, None] >= k_pos[None, :]
            prefix = k_pos[None, :] < 16
            return causal | prefix

        B, S, H, D = 2, 32, 2, 8
        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
        out = ring_self_attention(q, k, v, mesh, mask_fn=mask_fn)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D**-0.5)
        pos = jnp.arange(S)
        m = (pos[:, None] >= pos[None, :]) | (pos[None, :] < 16)
        s = jnp.where(m[None, None], s, -jnp.inf)
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(out, ref, atol=2e-5)


class TestMoE:
    def test_expert_parallel_matches_dense_top1(self):
        E, M, H = 8, 16, 32
        params = init_moe_params(jax.random.PRNGKey(1), E, M, H)
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, M))
        flat = x.reshape(-1, M)
        logits = flat @ params.gate
        probs = jax.nn.softmax(logits, -1)
        idx = jnp.argmax(probs, -1)
        gv = jnp.take_along_axis(probs, idx[:, None], 1)[:, 0]
        h = jax.nn.gelu(jnp.einsum("tm,tmh->th", flat, params.w_up[idx]))
        dense = (
            jnp.einsum("th,thm->tm", h, params.w_down[idx]) * gv[:, None]
        )
        mesh = build_mesh(MeshConfig(dp=2, ep=4))
        out, aux = moe_layer(params, x, mesh, capacity_factor=8.0)
        np.testing.assert_allclose(
            out.reshape(-1, M), dense, atol=2e-5
        )
        assert float(aux["balance"]) > 0
        assert float(aux["z"]) > 0

    def test_expert_parallel_matches_dense_top2(self):
        """EP top-2 == the dense reference: route each token to its two
        best experts with sum-normalized gates."""
        E, M, H = 8, 16, 32
        params = init_moe_params(jax.random.PRNGKey(5), E, M, H)
        x = jax.random.normal(jax.random.PRNGKey(6), (2, 16, M))
        flat = x.reshape(-1, M)
        probs = jax.nn.softmax(flat @ params.gate, -1)
        vals, idx = jax.lax.top_k(probs, 2)  # [T,2]
        gates = vals / (vals.sum(-1, keepdims=True) + 1e-9)
        dense = 0.0
        for r in range(2):
            e = idx[:, r]
            h = jax.nn.gelu(
                jnp.einsum("tm,tmh->th", flat, params.w_up[e])
            )
            dense += (
                jnp.einsum("th,thm->tm", h, params.w_down[e])
                * gates[:, r][:, None]
            )
        mesh = build_mesh(MeshConfig(dp=2, ep=4))
        out, aux = moe_layer(
            params, x, mesh, capacity_factor=8.0, top_k=2
        )
        np.testing.assert_allclose(
            np.asarray(out.reshape(-1, M)), np.asarray(dense), atol=2e-5
        )
        assert float(aux["balance"]) > 0 and float(aux["z"]) > 0

    def test_top2_capacity_priority_rank0_first(self):
        """A token's SECONDARY expert must not evict another token's
        primary assignment (GShard rank-priority rule): with capacity 1,
        every expert's single slot goes to a rank-0 claimant."""
        from dlrover_tpu.parallel.moe import topk_gating

        # token 0 prefers e0 then e1; token 1 prefers e1 then e0. With
        # capacity 1, token-major accounting would let token 0's
        # SECONDARY (e1) grab the slot token 1's PRIMARY needs; the
        # rank-major rule gives both primaries their slot and drops
        # both secondaries.
        logits = jnp.asarray(
            [[4.0, 2.0], [2.0, 4.0]], dtype=jnp.float32
        )
        dispatch, combine, _, _ = topk_gating(logits, 2, capacity=1, k=2)
        d = np.asarray(dispatch)  # [T, E, C]
        assert d[0, 0, 0] == 1  # token 0 primary kept
        assert d[1, 1, 0] == 1  # token 1 primary kept (NOT evicted)
        assert d.sum() == 2  # both secondaries dropped

    @pytest.mark.slow  # ~28s quality A/B (two full toy trainings);
    # routing correctness (dispatch/combine, capacity drops, EP-vs-
    # dense parity) stays tier-1 in the other TestMoE tests — budget
    def test_top2_beats_top1_on_toy_task(self):
        """Cluster-structured regression where each cluster needs TWO
        experts' capacity: training the tiny MoE LM with top-2 routing
        reaches lower loss than top-1 at equal steps."""
        from dlrover_tpu.models import (
            build_train_step, init_sharded_state, shard_batch, tiny,
        )
        import optax

        losses = {}
        for k in (1, 2):
            cfg = tiny(
                num_experts=4, moe_every=1, num_layers=2, moe_top_k=k,
                dtype="float32",
            )
            mesh = build_mesh(MeshConfig(ep=4, dp=2))
            tx = optax.adam(3e-3)
            state, _ = init_sharded_state(
                jax.random.PRNGKey(0), cfg, mesh, tx
            )
            step = build_train_step(cfg, mesh, tx)
            rng = np.random.default_rng(0)
            x = rng.integers(0, cfg.vocab_size, (8, 32)).astype(np.int32)
            b = shard_batch({"x": x, "y": x}, mesh)
            for _ in range(30):
                state, m = step(state, b["x"], b["y"])
            losses[k] = float(m["loss"])
            assert "moe_balance_loss" in m and "moe_z_loss" in m
        assert losses[2] < losses[1], losses

    def test_capacity_drops_are_partial_not_wrong(self):
        E, M, H = 4, 8, 16
        params = init_moe_params(jax.random.PRNGKey(3), E, M, H)
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 32, M))
        mesh = build_mesh(MeshConfig(ep=4, dp=2))
        out, _ = moe_layer(params, x, mesh, capacity_factor=0.5)
        assert np.isfinite(np.asarray(out)).all()


class TestModelParallelism:
    """Sharded forward == single-device forward for every mesh layout."""

    @pytest.mark.parametrize(
        "mesh_cfg",
        [
            MeshConfig(dp=8),
            MeshConfig(fsdp=8),
            MeshConfig(dp=2, fsdp=2, tp=2),
            MeshConfig(sp=4, tp=2),
        ],
        ids=["dp", "fsdp", "dp-fsdp-tp", "sp-tp"],
    )
    def test_forward_invariant_to_mesh(self, mesh_cfg):
        cfg = tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.asarray(_tokens(B=8, T=64))
        ref_logits, _ = forward(params, tokens, cfg, mesh=None)

        mesh = build_mesh(mesh_cfg)
        from dlrover_tpu.parallel.sharding_rules import apply_rules

        sh = apply_rules(logical_axes(cfg), default_lm_rules(), mesh)
        params_s = jax.device_put(params, sh)
        from dlrover_tpu.parallel.mesh import batch_sharding

        tok_s = jax.device_put(tokens, batch_sharding(mesh))
        logits, _ = jax.jit(
            lambda p, t: forward(p, t, cfg, mesh=mesh)
        )(params_s, tok_s)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(ref_logits), atol=3e-4
        )

    def test_train_step_loss_decreases(self):
        cfg = tiny()
        mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
        tx = optax.adamw(1e-3)
        state, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, mesh, tx
        )
        step_fn = build_train_step(cfg, mesh, tx)
        t = _tokens()
        batch = shard_batch({"x": t, "y": t}, mesh)
        losses = []
        for _ in range(8):
            state, m = step_fn(state, batch["x"], batch["y"])
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        assert int(state.step) == 8

    def test_moe_model_trains(self):
        cfg = tiny(num_experts=4, moe_every=2)
        mesh = build_mesh(MeshConfig(dp=2, ep=4))
        tx = optax.adamw(1e-3)
        state, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, mesh, tx
        )
        step_fn = build_train_step(cfg, mesh, tx)
        t = _tokens()
        batch = shard_batch({"x": t, "y": t}, mesh)
        losses = []
        for _ in range(6):
            state, m = step_fn(state, batch["x"], batch["y"])
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_remat_same_loss(self):
        cfg = tiny()
        cfg_r = tiny(remat=True)
        params = init_params(jax.random.PRNGKey(0), cfg)
        t = jnp.asarray(_tokens(B=2, T=32))
        from dlrover_tpu.models.transformer import loss_fn

        l0 = loss_fn(params, t, t, cfg)
        l1 = loss_fn(params, t, t, cfg_r)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)

    def test_gpt2_arch_forward(self):
        cfg = tiny(
            rope=False,
            rmsnorm=False,
            swiglu=False,
            tie_embeddings=True,
            num_kv_heads=None,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        t = jnp.asarray(_tokens(B=2, T=32))
        logits, _ = forward(params, t, cfg)
        assert logits.shape == (2, 32, cfg.vocab_size)
        assert "lm_head" not in params
        assert "positions" in params["embed"]


class TestOptStateShardings:
    def test_square_mlp_moments_inherit_param_sharding(self):
        """w_up (d,f) and w_down (f,d) with d == f have identical
        (shape, dtype): a shape-keyed lookup would alias their optimizer
        moments to one sharding. The structural path match must give each
        moment exactly its param's sharding."""
        import optax
        from dlrover_tpu.models import tiny
        from dlrover_tpu.models.train import state_shardings
        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

        cfg = tiny(mlp_dim=32)  # mlp_dim == model_dim -> square w_up/w_down
        mesh = build_mesh(MeshConfig(fsdp=2, tp=2, dp=2))
        tx = optax.adamw(1e-3)
        sh = state_shardings(cfg, mesh, tx)

        flat_p = {
            tuple(str(k) for k in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(sh.params)[0]
        }
        opt_flat = jax.tree_util.tree_flatten_with_path(sh.opt_state)[0]
        moment_leaves = [
            (path, s)
            for path, s in opt_flat
            if any(".mu" in str(k) or ".nu" in str(k) for k in path)
        ]
        assert moment_leaves, "expected adam mu/nu leaves"
        checked = 0
        for path, s in moment_leaves:
            key = tuple(str(k) for k in path)
            for start in range(len(key)):
                if key[start:] in flat_p:
                    assert s == flat_p[key[start:]], (
                        f"moment {key} sharded {s}, param {flat_p[key[start:]]}"
                    )
                    checked += 1
                    break
        assert checked == 2 * len(flat_p)


@pytest.mark.slow  # ~13s: double compile for parity; budget-gated out
def test_grad_accum_matches_full_batch():
    """K-microbatch accumulation == one full-batch step (same data,
    same update) to float tolerance."""
    import optax
    from dlrover_tpu.models import build_train_step, init_sharded_state

    cfg = tiny(num_layers=2, dtype="float32")
    mesh = build_mesh(MeshConfig(dp=8))
    tx = optax.adamw(1e-2)
    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)

    s1, _ = init_sharded_state(jax.random.PRNGKey(0), cfg, mesh, tx)
    s2, _ = init_sharded_state(jax.random.PRNGKey(0), cfg, mesh, tx)
    full = build_train_step(cfg, mesh, tx, donate=False)
    accum = build_train_step(cfg, mesh, tx, donate=False, grad_accum=4)
    s1, m1 = full(s1, x, x)
    s2, m2 = accum(s2, x, x)
    # fp32 reduction-order noise only (microbatch-mean vs full-batch)
    np.testing.assert_allclose(
        float(m1["loss"]), float(m2["loss"]), rtol=1e-5
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        ),
        s1.params,
        s2.params,
    )


class TestUlyssesAttention:
    """All-to-all sequence parallelism == dense attention (the
    DeepSpeed-Ulysses scheme, the ring's sibling)."""

    def _ref(self, q, k, v, causal):
        return _dense_attention_ref(q, k, v, causal=causal)

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        from dlrover_tpu.parallel.ulysses import ulysses_self_attention

        mesh = build_mesh(MeshConfig(dp=2, sp=4))
        B, S, H, D = 4, 32, 4, 8
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
        out = ulysses_self_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, k, v, causal)),
            atol=2e-5,
        )

    def test_gqa_and_matches_ring(self):
        from dlrover_tpu.parallel.ulysses import ulysses_self_attention

        # under tp the head axis is ALSO sharded: (H/tp) % sp == 0
        mesh = build_mesh(MeshConfig(sp=4, tp=2))
        B, S, H, Hkv, D = 2, 32, 8, 4, 8
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(ks[0], (B, S, H, D))
        k = jax.random.normal(ks[1], (B, S, Hkv, D))
        v = jax.random.normal(ks[2], (B, S, Hkv, D))
        out_u = ulysses_self_attention(q, k, v, mesh, causal=True)
        out_r = ring_self_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(out_u), np.asarray(out_r), atol=2e-5
        )

    def test_custom_mask(self):
        from dlrover_tpu.parallel.ulysses import ulysses_self_attention

        mesh = build_mesh(MeshConfig(dp=2, sp=4))

        def mask_fn(q_pos, k_pos):
            return (q_pos[:, None] >= k_pos[None, :]) | (
                k_pos[None, :] < 16
            )

        B, S, H, D = 2, 32, 4, 8
        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
        out = ulysses_self_attention(q, k, v, mesh, mask_fn=mask_fn)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D**-0.5)
        pos = jnp.arange(S)
        m = (pos[:, None] >= pos[None, :]) | (pos[None, :] < 16)
        s = jnp.where(m[None, None], s, -jnp.inf)
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_rejects_indivisible_heads(self):
        from dlrover_tpu.parallel.ulysses import ulysses_self_attention

        mesh = build_mesh(MeshConfig(sp=8))
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 4, 8))
        with pytest.raises(Exception, match="divide the local head"):
            jax.block_until_ready(
                ulysses_self_attention(q, q, q, mesh)
            )

    def test_fully_masked_rows_are_zero_not_nan(self):
        """Parity with the ring's masked-row guard: a query row whose
        mask hides every key yields zeros, never NaN."""
        from dlrover_tpu.parallel.ulysses import ulysses_self_attention

        mesh = build_mesh(MeshConfig(dp=2, sp=4))

        def mask_fn(q_pos, k_pos):
            # rows >= 16 see nothing at all
            return (q_pos[:, None] >= k_pos[None, :]) & (
                q_pos[:, None] < 16
            )

        B, S, H, D = 2, 32, 4, 8
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
        out_u = np.asarray(
            ulysses_self_attention(q, k, v, mesh, mask_fn=mask_fn)
        )
        out_r = np.asarray(
            ring_self_attention(q, k, v, mesh, mask_fn=mask_fn)
        )
        assert np.isfinite(out_u).all()
        np.testing.assert_array_equal(out_u[:, 16:], 0.0)
        np.testing.assert_allclose(out_u, out_r, atol=2e-5)

    def test_unexpanded_gqa_wire_path(self):
        """The headline GQA optimization: kv heads all-to-all UNEXPANDED
        when sp divides the local kv head count (here 4/tp2=2, sp=2),
        relying on the kernel's GQA head mapping after the wire."""
        from dlrover_tpu.parallel.ulysses import ulysses_self_attention

        mesh = build_mesh(MeshConfig(sp=2, tp=2, dp=2))
        B, S, H, Hkv, D = 2, 32, 8, 4, 8
        ks = jax.random.split(jax.random.PRNGKey(4), 3)
        q = jax.random.normal(ks[0], (B, S, H, D))
        k = jax.random.normal(ks[1], (B, S, Hkv, D))
        v = jax.random.normal(ks[2], (B, S, Hkv, D))
        out_u = ulysses_self_attention(q, k, v, mesh, causal=True)
        out_r = ring_self_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(out_u), np.asarray(out_r), atol=2e-5
        )

    def test_kernel_path_and_grads(self):
        """The TPU-training path: the Pallas kernel (interpret mode off
        TPU) inside the all-to-alls, and gradients through the whole
        scheme match the reference path's."""
        from dlrover_tpu.parallel.ulysses import ulysses_self_attention

        mesh = build_mesh(MeshConfig(dp=2, sp=4))
        B, S, H, D = 2, 32, 4, 8
        ks = jax.random.split(jax.random.PRNGKey(5), 3)
        q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)

        def loss(use_kernel):
            def f(q_):
                out = ulysses_self_attention(
                    q_, k, v, mesh, causal=True, use_kernel=use_kernel
                )
                return jnp.sum(out**2)

            return f

        out_k = ulysses_self_attention(q, k, v, mesh, use_kernel=True)
        out_r = ulysses_self_attention(q, k, v, mesh, use_kernel=False)
        np.testing.assert_allclose(
            np.asarray(out_k), np.asarray(out_r), atol=2e-4
        )
        # gradient PARITY between the kernel backward and plain AD,
        # both through the two all-to-alls (the TPU training path)
        gk = jax.grad(loss(True))(q)
        gr = jax.grad(loss(False))(q)
        np.testing.assert_allclose(
            np.asarray(gk), np.asarray(gr), atol=5e-3
        )
        assert float(jnp.sum(jnp.abs(gr))) > 0

    def test_model_sp_scheme_config(self):
        """cfg.sp_scheme='ulysses' routes the MODEL's attention through
        the all-to-all scheme and matches the ring-scheme forward."""
        cfg = tiny(num_heads=4, num_kv_heads=4)
        mesh = build_mesh(MeshConfig(sp=4, dp=2))
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.asarray(_tokens(B=4, T=32))
        ring_logits, _ = jax.jit(
            lambda p, t: forward(p, t, cfg, mesh)
        )(params, tokens)
        ucfg = tiny(num_heads=4, num_kv_heads=4, sp_scheme="ulysses")
        uly_logits, _ = jax.jit(
            lambda p, t: forward(p, t, ucfg, mesh)
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(uly_logits), np.asarray(ring_logits), atol=3e-5
        )
        # a typo'd scheme fails loudly instead of silently running ring
        bad = tiny(num_heads=4, num_kv_heads=4, sp_scheme="ulyses")
        with pytest.raises(Exception, match="unknown sp_scheme"):
            jax.block_until_ready(
                jax.jit(lambda p, t: forward(p, t, bad, mesh))(
                    params, tokens
                )
            )


class TestHybridDcnMesh:
    def test_dcn_axes_outermost_on_virtual_devices(self):
        """dcn_axes must survive virtual backends (no slice metadata):
        the fallback lays DCN axes with the LARGEST device strides so
        "slices" (consecutive device ids) stay adjacent on ICI axes."""
        mesh = build_mesh(
            MeshConfig(dp=2, fsdp=2, tp=2, dcn_axes=("dp",)),
            devices=jax.devices()[:8],
        )
        devs = mesh.devices  # [pp, dp, fsdp, ep, sp, tp]
        ids = np.vectorize(lambda d: d.id)(devs).squeeze()
        # ids shape [dp, fsdp, tp]: dp stride (DCN) = 4, the largest;
        # each dp slice holds one contiguous id block (one "slice")
        assert ids.shape == (2, 2, 2)
        assert set(ids[0].ravel()) == {0, 1, 2, 3}
        assert set(ids[1].ravel()) == {4, 5, 6, 7}

    @pytest.fixture
    def _sharding_invariant_rng(self):
        """Modern jax defaults partitionable threefry, making random
        values sharding-invariant; 0.4.x defaults it off, so the same
        key inits DIFFERENT weights on the hybrid vs single-device mesh
        and the loss-parity assertion below is vacuous noise. Flip it
        locally (globally it would route RNG through partition-id
        lowering the old XLA rejects inside manual shard_map regions)."""
        old = jax.config.jax_threefry_partitionable
        jax.config.update("jax_threefry_partitionable", True)
        yield
        jax.config.update("jax_threefry_partitionable", old)

    @pytest.mark.slow  # ~21s: 2-slice hybrid-mesh compile; budget-gated out
    def test_train_step_on_hybrid_mesh(self, _sharding_invariant_rng):
        """A real train step compiles and runs on the 2-slice hybrid
        mesh and matches the single-device result (layout, not math)."""
        cfg = tiny(num_experts=0)
        tx = optax.adamw(1e-3)
        mesh = build_mesh(
            MeshConfig(dp=2, fsdp=2, tp=2, dcn_axes=("dp",)),
            devices=jax.devices()[:8],
        )
        state, _ = init_sharded_state(jax.random.PRNGKey(0), cfg, mesh, tx)
        step = build_train_step(cfg, mesh, tx)
        tokens = _tokens(B=8, T=64, vocab=cfg.vocab_size)
        b = shard_batch({"x": tokens, "y": tokens}, mesh)
        state, metrics = step(state, b["x"], b["y"])
        hybrid_loss = float(metrics["loss"])

        ref_mesh = build_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
        ref_state, _ = init_sharded_state(
            jax.random.PRNGKey(0), cfg, ref_mesh, tx
        )
        ref_step = build_train_step(cfg, ref_mesh, tx)
        rb = shard_batch({"x": tokens, "y": tokens}, ref_mesh)
        ref_state, ref_metrics = ref_step(ref_state, rb["x"], rb["y"])
        np.testing.assert_allclose(
            hybrid_loss, float(ref_metrics["loss"]), rtol=2e-5
        )
