"""Continuous-batching engine vs the batch-synchronous generator: the
slot machinery (chunked prefill, in-graph refill, EOS stop) must be
invisible in the outputs — greedy decode of each prompt must match
``generate`` run on that prompt alone."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import tiny
from dlrover_tpu.models.transformer import init_params
from dlrover_tpu.rl.continuous_batching import continuous_generate
from dlrover_tpu.rl.generation import _mask_logits, generate


@pytest.fixture(scope="module")
def model():
    cfg = tiny(vocab_size=61, num_layers=2, max_seq_len=64)
    params = jax.jit(lambda k: init_params(k, cfg))(jax.random.PRNGKey(3))
    return cfg, params


def _prompt_queue(n, p_max, vocab, seed=0):
    """n prompts of varied lengths 2..p_max, right-padded."""
    rng = np.random.default_rng(seed)
    lens = rng.integers(2, p_max + 1, size=n)
    toks = np.zeros((n, p_max), np.int32)
    for i, ln in enumerate(lens):
        toks[i, :ln] = rng.integers(1, vocab, size=ln)
    return jnp.asarray(toks), jnp.asarray(lens.astype(np.int32))


class TestGreedyEquivalence:
    @pytest.mark.slow  # ~9s; bench --smoke gates the same bitwise claim
    def test_matches_single_prompt_generate(self, model):
        cfg, params = model
        N, P_max, new = 5, 10, 6
        prompts, lens = _prompt_queue(N, P_max, cfg.vocab_size)
        out_tokens, out_logps, out_lens = continuous_generate(
            params, prompts, lens, jax.random.PRNGKey(0), cfg,
            max_new_tokens=new, slots=2, greedy=True,
        )
        for i in range(N):
            ln = int(lens[i])
            ref_tokens, ref_logps = generate(
                params, prompts[i : i + 1, :ln], jax.random.PRNGKey(0),
                cfg, max_new_tokens=new, greedy=True,
            )
            assert int(out_lens[i]) == ln + new
            np.testing.assert_array_equal(
                np.asarray(out_tokens[i, : ln + new]),
                np.asarray(ref_tokens[0]),
            )
            np.testing.assert_allclose(
                np.asarray(out_logps[i]),
                np.asarray(ref_logps[0]),
                rtol=2e-4, atol=2e-5,
            )

    @pytest.mark.slow  # ~10s; refill path also covered by determinism tests
    def test_more_prompts_than_slots_refills(self, model):
        # N >> slots forces multiple refill waves through one slot
        cfg, params = model
        N, P_max, new = 9, 6, 4
        prompts, lens = _prompt_queue(N, P_max, cfg.vocab_size, seed=7)
        out_tokens, _, out_lens = continuous_generate(
            params, prompts, lens, jax.random.PRNGKey(0), cfg,
            max_new_tokens=new, slots=2, greedy=True,
        )
        for i in range(N):
            ln = int(lens[i])
            ref_tokens, _ = generate(
                params, prompts[i : i + 1, :ln], jax.random.PRNGKey(0),
                cfg, max_new_tokens=new, greedy=True,
            )
            np.testing.assert_array_equal(
                np.asarray(out_tokens[i, : ln + new]),
                np.asarray(ref_tokens[0]),
            )


class TestEos:
    def test_stops_at_eos_and_keeps_it(self, model):
        cfg, params = model
        N, P_max, new = 3, 8, 6
        prompts, lens = _prompt_queue(N, P_max, cfg.vocab_size, seed=1)
        # find what greedy decode produces for prompt 0, pick its 3rd
        # generated token as "EOS"
        ln0 = int(lens[0])
        ref_tokens, _ = generate(
            params, prompts[0:1, :ln0], jax.random.PRNGKey(0), cfg,
            max_new_tokens=new, greedy=True,
        )
        eos = int(ref_tokens[0, ln0 + 2])
        out_tokens, out_logps, out_lens = continuous_generate(
            params, prompts, lens, jax.random.PRNGKey(0), cfg,
            max_new_tokens=new, slots=3, greedy=True, eos_id=eos,
        )
        # prompt 0 must stop right after emitting the EOS token
        assert int(out_lens[0]) == ln0 + 3
        assert int(out_tokens[0, ln0 + 2]) == eos
        # logps past the stop are zero-padded
        np.testing.assert_array_equal(
            np.asarray(out_logps[0, 3:]), np.zeros(new - 3, np.float32)
        )
        # other prompts keep their full budget unless they also hit eos
        for i in range(1, N):
            assert int(out_lens[i]) <= int(lens[i]) + new

    def test_no_eos_runs_full_budget(self, model):
        cfg, params = model
        N, P_max, new = 4, 6, 5
        prompts, lens = _prompt_queue(N, P_max, cfg.vocab_size, seed=2)
        _, _, out_lens = continuous_generate(
            params, prompts, lens, jax.random.PRNGKey(0), cfg,
            max_new_tokens=new, slots=4, greedy=True,
        )
        np.testing.assert_array_equal(
            np.asarray(out_lens), np.asarray(lens) + new
        )


class TestSampled:
    def test_sampling_respects_support_restriction(self, model):
        # top_k=1 sampling == greedy decode, regardless of temperature
        cfg, params = model
        N, P_max, new = 4, 6, 4
        prompts, lens = _prompt_queue(N, P_max, cfg.vocab_size, seed=5)
        out_g, _, _ = continuous_generate(
            params, prompts, lens, jax.random.PRNGKey(0), cfg,
            max_new_tokens=new, slots=2, greedy=True,
        )
        out_k1, _, _ = continuous_generate(
            params, prompts, lens, jax.random.PRNGKey(0), cfg,
            max_new_tokens=new, slots=2, temperature=0.7, top_k=1,
        )
        np.testing.assert_array_equal(
            np.asarray(out_g), np.asarray(out_k1)
        )

    def test_rejects_bad_knobs(self, model):
        cfg, params = model
        prompts, lens = _prompt_queue(2, 4, cfg.vocab_size)
        with pytest.raises(ValueError, match="top_p"):
            continuous_generate(
                params, prompts, lens, jax.random.PRNGKey(0), cfg,
                top_p=0.0,
            )


class TestMaskLogits:
    """Edge cases of the vLLM-style support restriction: top_k=0 and
    top_p=1.0 are keep-all, the nucleus boundary token stays in, and
    composed knobs renormalize over the top-k restriction first."""

    def _logits(self, probs):
        # softmax(log p) == p, so tests can reason in probabilities
        return jnp.log(jnp.asarray([probs], jnp.float32))

    def test_topk_zero_topp_one_is_identity(self):
        logits = jnp.asarray([[0.5, -1.0, 2.0, 0.0]], jnp.float32)
        out = _mask_logits(logits, 0, 1.0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(logits))

    def test_topk_larger_than_vocab_clamps_to_keep_all(self):
        logits = jnp.asarray([[0.5, -1.0, 2.0, 0.0]], jnp.float32)
        out = _mask_logits(logits, 99, 1.0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(logits))

    def test_topk_only_keeps_exactly_k(self):
        logits = jnp.asarray([[0.1, 3.0, 2.0, -1.0, 0.5]], jnp.float32)
        out = np.asarray(_mask_logits(logits, 2, 1.0))
        finite = np.isfinite(out[0])
        assert set(np.nonzero(finite)[0]) == {1, 2}
        np.testing.assert_array_equal(out[0][finite], [3.0, 2.0])

    def test_nucleus_boundary_token_stays(self):
        # probs .5/.3/.15/.05, p=0.6: keep while PRECEDING mass < p —
        # token 1 crosses 0.6 and stays (the nucleus definition);
        # token 2's preceding mass is 0.8, out
        out = np.asarray(_mask_logits(self._logits([0.5, 0.3, 0.15, 0.05]), 0, 0.6))
        np.testing.assert_array_equal(
            np.isfinite(out[0]), [True, True, False, False]
        )

    def test_nucleus_tiny_p_keeps_argmax(self):
        out = np.asarray(_mask_logits(self._logits([0.2, 0.5, 0.3]), 0, 1e-6))
        np.testing.assert_array_equal(
            np.isfinite(out[0]), [False, True, False]
        )

    def test_topk_then_nucleus_composes_renormalized(self):
        # probs .4/.3/.2/.1 with top_k=2, top_p=0.5: the nucleus runs
        # over the RESTRICTED renormalized distribution [.571, .429] —
        # token 1's preceding mass is .571 >= .5, so only token 0
        # survives. Nucleus alone at p=0.5 would keep two tokens.
        logits = self._logits([0.4, 0.3, 0.2, 0.1])
        combined = np.asarray(_mask_logits(logits, 2, 0.5))
        np.testing.assert_array_equal(
            np.isfinite(combined[0]), [True, False, False, False]
        )
        nucleus_only = np.asarray(_mask_logits(logits, 0, 0.5))
        np.testing.assert_array_equal(
            np.isfinite(nucleus_only[0]), [True, True, False, False]
        )

    def test_rows_masked_independently(self):
        logits = jnp.log(jnp.asarray(
            [[0.5, 0.3, 0.15, 0.05], [0.05, 0.15, 0.3, 0.5]], jnp.float32
        ))
        out = np.asarray(_mask_logits(logits, 0, 0.6))
        np.testing.assert_array_equal(
            np.isfinite(out[0]), [True, True, False, False]
        )
        np.testing.assert_array_equal(
            np.isfinite(out[1]), [False, False, True, True]
        )


class TestDeterministicSeeds:
    """Sampling inside ``continuous_generate`` folds the key per decode
    step: the whole rollout is a pure function of (params, prompts,
    key) — the serving plane relies on this for replayable decodes."""

    def test_same_key_bitwise_identical(self, model):
        cfg, params = model
        prompts, lens = _prompt_queue(4, 6, cfg.vocab_size, seed=9)
        runs = [
            continuous_generate(
                params, prompts, lens, jax.random.PRNGKey(42), cfg,
                max_new_tokens=4, slots=2, temperature=0.8,
                top_k=5, top_p=0.9,
            )
            for _ in range(2)
        ]
        for a, b in zip(runs[0], runs[1]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_different_key_differs(self, model):
        cfg, params = model
        prompts, lens = _prompt_queue(4, 6, cfg.vocab_size, seed=9)
        out = [
            continuous_generate(
                params, prompts, lens, jax.random.PRNGKey(k), cfg,
                max_new_tokens=4, slots=2, temperature=0.8,
                top_k=5, top_p=0.9,
            )[0]
            for k in (42, 43)
        ]
        assert not np.array_equal(np.asarray(out[0]), np.asarray(out[1]))
